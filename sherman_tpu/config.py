"""Configuration for the TPU DSM + B+Tree stack.

Mirrors the reference's compile-time constant surface (``include/Common.h``,
``include/Config.h``) as runtime dataclasses, so one build serves tests
(8 virtual CPU devices) and real TPU meshes.
"""

from __future__ import annotations

import dataclasses

from sherman_tpu.errors import ConfigError

# ---------------------------------------------------------------------------
# Word / page geometry.
#
# The reference uses 1 KB pages (Common.h:119-121).  We keep 1 KB pages but
# express everything in 32-bit words: TPUs have no native int64 lanes, so
# 64-bit keys/values/pointers are stored as pairs of int32 words (bit-pattern
# of the uint64 hi/lo halves).
# ---------------------------------------------------------------------------

PAGE_BYTES = 1024
PAGE_WORDS = PAGE_BYTES // 4  # 256 int32 words per page

# Packed 32-bit global page address {node:8, page:24} — the TPU analogue of
# the reference's 64-bit GlobalAddress {nodeID:16, offset:48}
# (GlobalAddress.h:10-16).  addr==0 is NULL; page 0 of node 0 is reserved
# (it holds the root pointer + cluster meta words, cf. the reference's fixed
# root-pointer slot at node 0, kChunkSize/2 — Tree.cpp:90-97, Common.h:82-84).
ADDR_NODE_BITS = 8
ADDR_PAGE_BITS = 24
ADDR_PAGE_MASK = (1 << ADDR_PAGE_BITS) - 1
MAX_MACHINE = 1 << ADDR_NODE_BITS

# Meta words inside the reserved page 0 of node 0.  The root's level is NOT
# mirrored here: it is read from the root page's own W_LEVEL word, so the
# root install stays a single atomic CAS on this one word.
META_ROOT_ADDR_W = 0   # packed addr of the current root page


def donate_argnums(*argnums: int) -> tuple[int, ...]:
    """Buffer-donation argnums for jit, gated by backend.

    Donation is a pure optimization (the output reuses the input's
    buffer in place).  On this toolchain's CPU backend, donated-input
    aliasing is unstable under suite-level churn: with donation on, the
    CPU test suite intermittently reads corrupt pool/meta words or
    segfaults inside result materialization in tests that run AFTER a
    donation-heavy test, at identical code — classic freed-buffer reuse
    while an earlier donated execution is still completing.  Off-CPU
    (TPU) donation is load-bearing (avoids copying the pool every step)
    and unaffected; CPU pools in tests are small, so the copies are
    noise there.  Call at jit-CONSTRUCTION time, never import time (it
    initializes the backend, which must stay after
    jax.distributed.initialize in multihost drivers)."""
    import jax
    return argnums if jax.default_backend() != "cpu" else ()


def staged_fusion() -> str:
    """Compiled-program structure of the device-staged benchmark step
    (``workload.device_prep.make_staged_step``), from the
    ``SHERMAN_STAGED_FUSION`` env var:

    - ``aligned`` (default): prep -> serve -> verify, where the serve
      is the ENGINE's host-staged combined-search fan-out program — the
      same compiled executable the throughput phase runs, so the staged
      serve's input layouts/donation/HLO match the host-staged case by
      construction (the round-6 answer to BENCHMARKS.md's round-5
      "known headroom" suspects).
    - ``pipelined``: the SAME three programs as ``aligned`` (same
      compiled serve object — the CI program-identity pin extends to
      it), dispatched as a two-deep software pipeline: while the
      device serves batch k, the host has already dispatched prep for
      batch k+1 and consumes (verifies) batch k-1's materialized
      answers, so the prep/verify walls hide behind the serve wherever
      the backend overlaps independent programs.  Per-batch receipts
      stay bit-identical to ``aligned`` (pipeline drained via
      ``step.drain``).  Stays non-default until the queued chip A/B
      lands (BENCHMARKS.md "Chip-session queue").
    - ``chained``: the round-5 two-program form (fan-out + verification
      fused into the serve program), kept for A/B measurement.
    - ``fused``: one jitted program — the CPU-mesh regression form
      (proves no host round trip between generation and serve); on TPU
      the known XLA pathology makes it 50-100x slower (BENCHMARKS.md).

    Buffer donation inside every form stays gated by
    :func:`donate_argnums` (CPU donation is unstable on this
    toolchain)."""
    import os
    v = os.environ.get("SHERMAN_STAGED_FUSION", "aligned").lower()
    if v not in ("aligned", "pipelined", "chained", "fused"):
        raise ConfigError(
            f"SHERMAN_STAGED_FUSION={v!r}: want "
            "aligned|pipelined|chained|fused")
    return v


def prep_impl() -> str:
    """Request-plane placement knob (``SHERMAN_PREP_IMPL``): where the
    per-batch combining/dedup/sort/route prep of the serving front
    door's ingress step (``workload.device_prep.make_ingress_step``)
    runs.

    - ``host`` (default): the PR-13 path — ``np.unique`` dedup +
      host router probe, then the fused device fan-out.  Default per
      the measurement-driven-flips guardrail: the device path ships
      behind the chip A/B queued in BENCHMARKS.md.
    - ``device``: one fused device program sorts, dedups, and
      router-probes the raw request pairs (``lax.sort`` + segment
      scan), emitting staged inputs bit-identical to the host path
      (CI-pinned, including straggler/partial-active widths) with the
      host out of the per-batch path.  Falls back to ``host`` for
      steps constructed with a leaf cache attached: the cache probe is
      host-in/host-out (it syncs its hit count), so composing it with
      device prep would reintroduce the very host round-trip the knob
      removes."""
    import os
    v = os.environ.get("SHERMAN_PREP_IMPL", "host").strip().lower()
    if v not in ("host", "device"):
        raise ConfigError(
            f"SHERMAN_PREP_IMPL={v!r}: want host|device")
    return v


def write_combine() -> bool:
    """Write-combining knob (``SHERMAN_WRITE_COMBINE``): when on, the
    leaf-apply kernels consult each page-group's lock word ONCE per
    group instead of once per row — the TPU analog of Sherman's HOCL
    local-lock-table handover (many same-leaf writes ride one lock
    acquisition).  Statuses, acks, journal order, pool bits stay
    identical by construction (rows of one page hash to ONE lock word,
    so per-row verdicts within a group were always uniform); only the
    lock-consult count and the ``combine.*`` counters change.

    Off is the SHIPPED DEFAULT (standing guardrail: flips are
    measurement-driven — the chip A/B queued in BENCHMARKS.md decides
    it)."""
    import os
    v = os.environ.get("SHERMAN_WRITE_COMBINE", "0").strip().lower()
    if v in ("", "0", "false", "off", "no"):
        return False
    if v in ("1", "true", "on", "yes"):
        return True
    raise ConfigError(
        f"SHERMAN_WRITE_COMBINE={v!r}: want 0/1")


def value_heap_pages() -> int:
    """Out-of-line value heap knob (``SHERMAN_VALUE_HEAP``): heap pages
    per node of the second DSM region storing variable-length payloads
    (:mod:`sherman_tpu.models.value_heap`), 0 = disabled.

    Off is the SHIPPED DEFAULT: with the knob unset every leaf value is
    the inline 64-bit word pair it always was and every compiled
    program, pool image and bench receipt is bit-identical to a build
    without the subsystem (the heap-off identity pin in CI).
    ``SHERMAN_VALUE_HEAP=1`` enables the heap at the default region
    size; any larger integer is the heap pages-per-node count."""
    import os
    v = os.environ.get("SHERMAN_VALUE_HEAP", "0").strip().lower()
    if v in ("", "0", "false", "off", "no"):
        return 0
    if v in ("1", "true", "on", "yes"):
        return 4096
    try:
        n = int(v)
    except ValueError:
        raise ConfigError(
            f"SHERMAN_VALUE_HEAP={v!r}: want 0/1 or a pages-per-node "
            "count")
    if n < 0:
        raise ConfigError(f"SHERMAN_VALUE_HEAP={n}: want >= 0")
    return n


def leaf_cache_slots() -> int:
    """Hot-key tier knob (``SHERMAN_LEAF_CACHE``): physical slot count
    of the compute-side versioned leaf/value cache
    (:mod:`sherman_tpu.models.leaf_cache`), 0 = disabled.

    Off is the SHIPPED DEFAULT (standing guardrail: measurement-driven
    flips — the hot-key receipts in BENCHMARKS.md decide the default).
    ``SHERMAN_LEAF_CACHE=1`` enables the cache at the default table
    size; any larger integer is the physical slot count (rounded up to
    a power of two by the cache itself; admitted-key capacity is half
    the slots — open addressing at load <= 0.5 keeps the bounded probe
    window near-lossless)."""
    import os
    v = os.environ.get("SHERMAN_LEAF_CACHE", "0").strip().lower()
    if v in ("", "0", "false", "off", "no"):
        return 0
    if v in ("1", "true", "on", "yes"):
        return 65536
    try:
        n = int(v)
    except ValueError:
        raise ConfigError(
            f"SHERMAN_LEAF_CACHE={v!r}: want 0/1 or a slot count")
    if n < 0:
        raise ConfigError(f"SHERMAN_LEAF_CACHE={n}: want >= 0")
    return n


def replica_count() -> int:
    """Replication-plane knob (``SHERMAN_REPL``): number of in-process
    follower engines in the journal-shipped replica group
    (:mod:`sherman_tpu.replica`), 0 = disabled.

    Off is the SHIPPED DEFAULT (standing guardrail): with the knob
    unset no follower is constructed, no tailer polls, and the primary
    pool is bit-identical to a build without the subsystem (the
    replica-off identity pin in ``tests/test_replica.py``).
    ``SHERMAN_REPL=1`` runs one follower; any larger integer is the
    follower count."""
    import os
    v = os.environ.get("SHERMAN_REPL", "0").strip().lower()
    if v in ("", "0", "false", "off", "no"):
        return 0
    if v in ("1", "true", "on", "yes"):
        return 1
    try:
        n = int(v)
    except ValueError:
        raise ConfigError(
            f"SHERMAN_REPL={v!r}: want 0/1 or a follower count")
    if n < 0:
        raise ConfigError(f"SHERMAN_REPL={n}: want >= 0")
    return n


def replica_poll_ms() -> float:
    """Replication tail cadence knob (``SHERMAN_REPL_POLL_MS``): how
    often the follower tail polls the primary's live journal segment
    for newly shipped records (milliseconds; the background-thread
    mode of :class:`sherman_tpu.replica.ReplicaGroup` — drivers that
    pump synchronously ignore it).  Lower = fresher followers
    (smaller replication lag) at more filesystem polls."""
    import os
    v = os.environ.get("SHERMAN_REPL_POLL_MS", "20").strip()
    try:
        ms = float(v)
    except ValueError:
        raise ConfigError(
            f"SHERMAN_REPL_POLL_MS={v!r}: want a float of milliseconds")
    if ms <= 0:
        raise ConfigError(f"SHERMAN_REPL_POLL_MS={ms}: want > 0")
    return ms


def ack_quorum() -> int:
    """Quorum-ack knob (``SHERMAN_ACK_QUORUM``): how many DURABLE
    copies a write needs before its ack resolves — the primary's
    fsync'd journal record counts as 1, every follower whose applied
    watermark covers the record adds 1.

    1 is the SHIPPED DEFAULT (standing guardrail): primary-durability
    acks, bit-identical to the pre-quorum front door — the server
    never consults the replica group on the ack path (the quorum-off
    identity pin in ``tests/test_serve.py``).  ``K > 1`` gates every
    write ack on ``K - 1`` follower watermarks with a bounded wait
    (typed ``QuorumTimeoutError`` on expiry; the rid stays in the
    exactly-once window, so the client's retry re-acks the original
    result once replication catches up — never a re-apply)."""
    import os
    v = os.environ.get("SHERMAN_ACK_QUORUM", "1").strip().lower()
    if v in ("", "0", "1", "false", "off", "no"):
        return 1
    try:
        n = int(v)
    except ValueError:
        raise ConfigError(
            f"SHERMAN_ACK_QUORUM={v!r}: want a copy count >= 1")
    if n < 1:
        raise ConfigError(f"SHERMAN_ACK_QUORUM={n}: want >= 1")
    return n


def tail_wait_s() -> float:
    """Tailer stall watchdog knob (``SHERMAN_TAIL_WAIT_S``): how long
    a follower's journal tail may wait on a live torn frame (an
    append in flight) before probing the lease table.  A torn tail
    whose primary's lease is DEAD after this long is a stall, not an
    append — the tailer surfaces a typed ``TailStalledError`` (plus a
    flight event) instead of hanging the follower forever; a live
    primary keeps the wait (slow appends are legal, evented once)."""
    import os
    v = os.environ.get("SHERMAN_TAIL_WAIT_S", "5").strip()
    try:
        s = float(v)
    except ValueError:
        raise ConfigError(
            f"SHERMAN_TAIL_WAIT_S={v!r}: want a float of seconds")
    if s <= 0:
        raise ConfigError(f"SHERMAN_TAIL_WAIT_S={s}: want > 0")
    return s


def anti_entropy_s() -> float:
    """Anti-entropy audit cadence knob (``SHERMAN_ANTI_ENTROPY_S``):
    seconds between periodic follower audits (watermark freshness +
    consumed-segment CRC + sampled pool-page compare against the
    primary) in :class:`sherman_tpu.replica.AntiEntropy`'s background
    mode.  0 disables the background thread (the SHIPPED DEFAULT —
    drills and operators call ``tick()`` explicitly); a divergent
    follower is quarantined out of the read-serving set and re-shipped
    from the checkpoint chain + journal before re-admission."""
    import os
    v = os.environ.get("SHERMAN_ANTI_ENTROPY_S", "0").strip().lower()
    if v in ("", "0", "false", "off", "no"):
        return 0.0
    try:
        s = float(v)
    except ValueError:
        raise ConfigError(
            f"SHERMAN_ANTI_ENTROPY_S={v!r}: want a float of seconds")
    if s < 0:
        raise ConfigError(f"SHERMAN_ANTI_ENTROPY_S={s}: want >= 0")
    return s


def hosts() -> int:
    """Host-plane width knob (``SHERMAN_HOSTS``): how many hosts the
    multihost service plane spans — per-host journal/chain ownership,
    per-host ingress dispatchers, and key routing by owner host
    (``sherman_tpu/multihost.py``).

    1 is the SHIPPED DEFAULT (standing guardrail): no host plane — one
    front door, one journal stream, legacy un-tagged chain artifact
    names, bit-identical to a build without the plane.  ``N > 1``
    gives every host its own chain namespace (``base-h<i>.npz`` /
    ``delta-h<i>-...`` / ``journal-h<i>-...``) and one Nth of the key
    space; on CPU builds without multiprocess collectives the plane
    runs EMULATED (N host contexts in one process — the protocol/file
    paths are real, the transport is not)."""
    import os
    v = os.environ.get("SHERMAN_HOSTS", "1").strip().lower()
    if v in ("", "0", "1", "false", "off", "no"):
        return 1
    try:
        n = int(v)
    except ValueError:
        raise ConfigError(f"SHERMAN_HOSTS={v!r}: want a host count >= 1")
    if n < 1:
        raise ConfigError(f"SHERMAN_HOSTS={n}: want >= 1")
    return n


def host_id() -> int:
    """This process's host index knob (``SHERMAN_HOST_ID``): which
    host of the ``SHERMAN_HOSTS``-wide plane this process IS on a real
    pod (one process per host, each owning its chain namespace and
    key range).  0 is the SHIPPED DEFAULT and the only legal value
    when ``SHERMAN_HOSTS=1``; emulated (single-process) planes ignore
    it — they construct every host context themselves."""
    import os
    v = os.environ.get("SHERMAN_HOST_ID", "0").strip()
    try:
        h = int(v) if v else 0
    except ValueError:
        raise ConfigError(
            f"SHERMAN_HOST_ID={v!r}: want a host index >= 0")
    n = hosts()
    if not (0 <= h < n):
        raise ConfigError(
            f"SHERMAN_HOST_ID={h}: want in [0, SHERMAN_HOSTS={n})")
    return h


def host_lease_s() -> float:
    """Host-lease expiry knob (``SHERMAN_HOST_LEASE_S``): how long a
    host's durable heartbeat record in the shared chain directory
    (``sherman_tpu/hostlease.py``) stays live without a renewal before
    liveness probes judge the host DEAD — the cross-host twin of the
    client lease table's expiry discipline.  Expiry alone changes
    nothing durable; it licenses a surviving host to bump the dead
    host's lease epoch (the fence point) and adopt its chain
    namespace.  Too short risks adopting a merely-slow host (its
    post-adoption writes then fence typed — safe, but an availability
    blip); too long stretches the unserved window for the dead host's
    keys."""
    import os
    v = os.environ.get("SHERMAN_HOST_LEASE_S", "2").strip()
    try:
        s = float(v)
    except ValueError:
        raise ConfigError(
            f"SHERMAN_HOST_LEASE_S={v!r}: want a float of seconds")
    if s <= 0:
        raise ConfigError(f"SHERMAN_HOST_LEASE_S={s}: want > 0")
    return s


def host_probe_s() -> float:
    """Host liveness-probe cadence knob (``SHERMAN_HOST_PROBE_S``):
    seconds between background sweeps of the host lease table
    (``HostFailover.start``) looking for expired peers.  0 disables
    the background prober (the SHIPPED DEFAULT — drills and operators
    call ``detect()`` explicitly); a positive cadence should be well
    under ``SHERMAN_HOST_LEASE_S`` so expiry is noticed within one
    lease window."""
    import os
    v = os.environ.get("SHERMAN_HOST_PROBE_S", "0").strip().lower()
    if v in ("", "0", "false", "off", "no"):
        return 0.0
    try:
        s = float(v)
    except ValueError:
        raise ConfigError(
            f"SHERMAN_HOST_PROBE_S={v!r}: want a float of seconds")
    if s < 0:
        raise ConfigError(f"SHERMAN_HOST_PROBE_S={s}: want >= 0")
    return s


@dataclasses.dataclass(frozen=True)
class DSMConfig:
    """Cluster + memory-pool shape (reference ``Config.h:13-22``).

    ``machine_nr`` plays the role of DSMConfig::machineNR; the per-node pool
    is ``pages_per_node`` 1 KB pages of HBM instead of ``dsmSize`` GB of
    hugepages (DSM.cpp:40).
    """

    machine_nr: int = 1
    pages_per_node: int = 4096
    # Global lock table shard per node; the analogue of the 16K on-NIC
    # device-memory locks (kLockChipMemSize = 128 KB -> 16K 64-bit words,
    # Common.h:86-93).  Ours are 32-bit words.
    locks_per_node: int = 16384
    # Per-(source, destination) request capacity of one DSM step's
    # all_to_all exchange.  Requests over capacity are dropped with ok=0 and
    # retried by the caller (cf. RDMA send-queue depth).
    step_capacity: int = 512
    # Capacity of the HOST control-plane step (DSM.step/_batch): kept small
    # and independent of step_capacity because every host call materializes
    # [machine_nr * capacity, PAGE_WORDS] request payloads — sizing it like
    # the device batch would ship hundreds of MB per control-plane op.
    host_step_capacity: int = 64
    # Chunk size of the memory-node global allocator, in pages
    # (kChunkSize = 32 MB -> 32768 pages, Common.h:80).  Scaled down by
    # default so small test pools still have multiple chunks.
    chunk_pages: int = 256
    # Inter-node exchange implementation: "xla" = all_to_all collectives
    # (default); "pallas" = explicit per-peer one-sided remote-DMA writes
    # (transport_pallas.py — the literal RDMA-verbs analogue).
    exchange_impl: str = "xla"
    # Page-engine implementation — the HBM<->VMEM half of the explicit-
    # DMA story (exchange_impl is the inter-chip half): "xla" = native
    # gather/scatter primitives (default — the measured floors in
    # BENCHMARKS.md are theirs); "pallas" = the ops/pallas_page.py
    # kernel suite (fused descent round, multi-lane write-back,
    # snapshot gather).  Both produce bit-identical pools/results
    # (CI-pinned); flip per deployment from tools/profile_gather.py
    # measurements, not belief.
    gather_impl: str = "xla"
    # Out-of-line VALUE HEAP (models/value_heap.py): a second DSM
    # region of this many 1 KB pages per node, carved into size-class
    # slabs holding variable-length payloads; leaf slots then store
    # versioned HANDLES instead of inline values, resolved in the same
    # fused device step as the descent fan-out (gathered through
    # ``gather_impl`` like the pool).  0 (default) = no heap: every
    # program and artifact is bit-identical to a build without the
    # subsystem.  SHERMAN_VALUE_HEAP drives it in the bench/serve
    # drivers (config.value_heap_pages()).
    heap_pages_per_node: int = 0

    def __post_init__(self):
        assert 1 <= self.machine_nr <= MAX_MACHINE
        assert self.pages_per_node <= (1 << ADDR_PAGE_BITS)
        # Per-node pools are flat-indexed in int32 words on device (the
        # TPU-native word size): one node's partition must stay under
        # 2^31 words = 8 GB.  Larger clusters scale by adding NODES —
        # each node's HBM shard is addressed independently, which is the
        # architecture's scaling axis anyway (symmetric partitioning).
        assert self.pages_per_node * PAGE_WORDS < (1 << 31), (
            f"pages_per_node={self.pages_per_node} exceeds the 8 GB "
            "per-node pool limit (int32 word indexing); add nodes instead")
        assert self.exchange_impl in ("xla", "pallas")
        assert self.gather_impl in ("xla", "pallas")
        assert self.heap_pages_per_node >= 0
        assert self.heap_pages_per_node <= (1 << ADDR_PAGE_BITS)


# ---------------------------------------------------------------------------
# B+Tree page layout (word offsets inside a 256-word page).
#
# Mirrors the reference Header/InternalEntry/LeafEntry *content*
# (Tree.h:130-187) but NOT its array-of-structs layout: entries are stored
# struct-of-arrays WITHIN the page — each field is a contiguous word block —
# because TPU vector units have no per-lane gather: a strided field access
# (AoS) lowers to a slow minor-axis gather, while an SoA field is a static
# contiguous slice the VPU streams at full rate.  This is the single most
# important TPU-first layout decision in the framework (measured ~5x on the
# batched descent hot loop).
#
#   word 0:   front_version        (Tree.h:199-210 front/rear page versions)
#   word 1:   leftmost_ptr         (internal pages; Header.leftmost_ptr)
#   word 2:   sibling_ptr          (B-link; Header.sibling_ptr)
#   word 3:   level                (0 = leaf)
#   word 4:   nkeys                (Header.last_index + 1)
#   word 5-6: lowest key (hi, lo)  (fence keys, Header.lowest/highest)
#   word 7-8: highest key (hi, lo)
#   word 9..254: entry field blocks (SoA, see below)
#   word 255: rear_version
#
# Internal (82 entries): khi[82] | klo[82] | child[82]
# Leaf     (49 slots):   ver[49] | khi[49] | klo[49] | vhi[49] | vlo[49]
#
# ver packs the per-entry two-level version PAIR (LeafEntry
# f_version/r_version, Tree.h:174-187 — 4-bit there) as 16/16 bits of one
# word: fver = ver >> 16, rver = ver & 0xFFFF; a slot is live iff
# fver == rver != 0, ver == 0 marks a free slot.  One word instead of two
# cuts the update write-back scatter from 4 lanes to 3 (scatter cost is
# ~13.5 ms/lane at 2 M rows — the write path's #1 knob) and grows
# LEAF_CAP 41 -> 49 (+20% leaf density).  NOTE the invariant this buys:
# with both halves in one word, fver == rver can never observe a torn
# PAIR — the check degenerates to a single-word liveness marker
# (ver != 0, halves equal by construction) and certifies nothing about
# the other four entry words.  Entry tear-freedom rests on the DSM's
# whole-batch step atomicity plus step serialization (a writer's 3-word
# update lands in ONE step; readers see before or after, never between).
# Any change that splits a host write batch for one entry across steps
# loses that protection — it cannot lean on the version check.
# ---------------------------------------------------------------------------

W_FRONT_VER = 0
W_LEFTMOST = 1
W_SIBLING = 2
W_LEVEL = 3
W_NKEYS = 4
W_LOW_HI = 5
W_LOW_LO = 6
W_HIGH_HI = 7
W_HIGH_LO = 8
W_ENTRIES = 9
W_REAR_VER = PAGE_WORDS - 1

ENTRY_WORDS_AVAIL = W_REAR_VER - W_ENTRIES  # 246

INTERNAL_ENTRY_WORDS = 3  # words per internal entry (summed over blocks)
LEAF_ENTRY_WORDS = 5      # words per leaf slot (summed over blocks)

INTERNAL_CAP = ENTRY_WORDS_AVAIL // INTERNAL_ENTRY_WORDS  # 82 -> reference 61
LEAF_CAP = ENTRY_WORDS_AVAIL // LEAF_ENTRY_WORDS          # 49 -> reference 54

# Internal field block starts.
I_KHI_W = W_ENTRIES
I_KLO_W = I_KHI_W + INTERNAL_CAP
I_PTR_W = I_KLO_W + INTERNAL_CAP

# Leaf field block starts.  ver packs (fver << 16) | rver per slot.
L_VER_W = W_ENTRIES
L_KHI_W = L_VER_W + LEAF_CAP
L_KLO_W = L_KHI_W + LEAF_CAP
L_VHI_W = L_KLO_W + LEAF_CAP
L_VLO_W = L_VHI_W + LEAF_CAP

ENTRY_VER_MASK = 0xFFFF  # 16-bit per-entry versions; bumps skip 0

# 64-bit key sentinels (stored as hi/lo uint32 pairs).  User keys must lie in
# [KEY_MIN, KEY_MAX]; the fences use NEG_INF/POS_INF (cf. kKeyMin/kKeyMax in
# the reference tests).
KEY_NEG_INF = 0
KEY_POS_INF = (1 << 64) - 1
KEY_MIN = 1
KEY_MAX = KEY_POS_INF - 1


@dataclasses.dataclass(frozen=True)
class TreeConfig:
    """Tree-level knobs (reference ``Common.h:73-104`` namespace define)."""

    # Max tree height the batched device kernels unroll/loop over.
    max_level: int = 8
    # Extra descent iterations budgeted for B-link sibling chases per op.
    sibling_chase_budget: int = 4
    # Rounds of the device-side insert retry loop before falling back to the
    # host slow path.  Mass inserts into a small tree split at most one new
    # page per leaf per round (suppression), so leaf count doubles per
    # round: the budget covers ~2^16 leaves of growth from a cold tree.
    insert_rounds: int = 16
    # Bulk-load leaf fill fraction (cf. kWarmRatio=0.8, benchmark.cpp:19).
    bulk_fill: float = 0.75
    # Local lock table size for the hierarchical lock (kNumOfLock parity).
    hand_over_limit: int = 8  # kMaxHandOverTime, Common.h:101
    # Bounded lock retry (data-plane failure story): every this-many
    # consecutive rounds a device-insert row stays blocked on a HELD
    # page lock, the engine probes the lease table and revokes a DEAD
    # holder's lock (client died mid-critical-section).  Live holders
    # are normal contention and keep retrying (with host-side backoff)
    # through the round budget; rows still blocked when it runs out are
    # rejected with the typed ST_LOCK_TIMEOUT status instead of
    # spinning unboundedly in the host fallback.
    lock_retry_rounds: int = 3
