"""Serving front door: continuous batching with SLO-adaptive step width.

Every published number so far came from a closed-loop bench driver that
owns the whole machine; this module is the missing REQUEST PATH — the
piece that turns the engine into something millions of clients could
sit behind (the ROADMAP's "refactor that unlocks every millions-of-
users scenario").  An Orca/vLLM-style continuous-batching ingress:
independent client requests (read / insert / delete / scan) coalesce
into device steps, and the step WIDTH — the repo's one latency-vs-
throughput dial, measured as a frontier since round 4 — is chosen
ADAPTIVELY against a per-class p99 target instead of the bench's fixed
4 M-op batch.

Architecture (one dispatcher thread drives the device; clients only
enqueue):

- **Admission** (:meth:`ShermanServer.submit`, any thread): typed,
  synchronous backpressure.  A full queue — or a tenant exceeding its
  max-min fair share of it — raises :class:`ServeOverloadError`
  (beside the engine's existing ``ST_LOCK_TIMEOUT`` /
  :class:`~sherman_tpu.models.batched.DegradedError` typed rejects);
  writes are additionally shed FIRST under pressure (brownout, below).
  Admission does no device work and no allocation beyond the request
  record itself.
- **Continuous batching** (the dispatcher): pending read requests are
  coalesced — round-robin across tenants, FIFO within a tenant — into
  one device step of width ``W`` picked by the
  :class:`WidthController`, and dispatched through
  :func:`~sherman_tpu.workload.device_prep.make_ingress_step`: the
  host-fed twin of the ``fusion="pipelined"`` staged substrate, whose
  serve is the SAME compiled program object the staged loops and the
  host-staged throughput phase run.  With ``fusion="pipelined"``
  (default) ONE batch stays in flight: batch k's host prep + dispatch
  overlaps batch k-1's device serve, the two-deep discipline applied
  to external traffic; ``"aligned"`` completes each batch before the
  next dispatch (the sequential comparator).
- **Adaptive width**: the controller is seeded by a calibration sweep
  over the width ladder (closed-loop wall per rung — every rung is
  compiled and warmed HERE, which is what lets the loop seal) and
  refined online from each completed step's wall plus the
  ``obs.slo_window()`` / serve-tracker per-class p50/p99.  It picks
  the largest rung whose modeled p99 meets the target (throughput
  within the SLO), never a rung wider than the backlog needs, and
  steps down multiplicatively when the MEASURED window p99 breaches
  the target (the model is a guide; the tracker is the truth).
- **SEALED serving loop** (the PR 8 contract): after warmup the
  compile ledger is sealed — any retrace in steady state is a counted
  ``compile.retrace`` flight event, an auto-dumped black box, and a
  perfgate red.  The width ladder makes this possible: every compiled
  shape the loop can dispatch exists before ``seal()``.
- **Journaled by construction**: the write path acks a request ONLY
  after the engine op returns, and a journaled engine appends the
  op's record — fsync'd, group-committed under
  ``Journal(group_commit_ms=...)`` — before returning.  No code path
  exists that resolves a write future before a covering fsync; the
  crash drill (``tools/serve_bench.py --crash-drill``) pins
  ``rpo_ops == 0`` against the acked-op ledger.  Continuous batching
  is also what finally gives group commit its production shape: one
  batch record covers every client write it coalesced, so acks per
  fsync scale with the batch instead of 1.
- **Brownout — shed writes first**: degraded mode already proves the
  read path can serve alone, so pressure follows the same gradient.
  Above ``brownout_hi`` queue occupancy, write admissions get
  :class:`ServeOverloadError` while reads keep admitting to the full
  cap (hysteresis at ``brownout_lo``); on engine DEGRADED entry,
  write admissions AND already-queued writes fail with the typed
  :class:`~sherman_tpu.models.batched.DegradedError` while reads keep
  serving.  Both transitions are flight-recorded.
- **Telemetry**: per-REQUEST end-to-end latency (submit -> ack) lands
  in a dedicated :class:`~sherman_tpu.obs.slo.SloTracker` published as
  the ``serve.`` pull collector (``serve.read.p99_ms`` in every
  snapshot / scrape), beside admission/reject/tenant-share counters
  and the current width; the engine-side service walls still feed the
  default ``slo.`` tracker via ``obs.slo.observe`` — the controller
  consumes both.

- **Client contract** (PR 15 — the exactly-once / deadline / audit
  plane):

  - *exactly-once writes*: a write submitted with a client-assigned
    request id (``rid``) is applied AT MOST once no matter how often
    it is retried — a bounded per-tenant dedup window caches each
    acked rid's result (retry -> the ORIGINAL result re-acked, never a
    re-apply that could stomp a newer write), an in-flight rid returns
    the SAME future, and the window itself is journaled
    (``J_ACK`` batch records, appended post-apply pre-ack under the
    same fsync gate) so ``RecoveryPlane.recover`` reconstructs it
    across a cold crash (:meth:`ShermanServer.seed_dedup`);
  - *deadlines*: ``submit(..., deadline_ms=...)`` attaches a budget;
    requests still queued past it are shed BEFORE dispatch with the
    typed :class:`DeadlineExceededError` — never silently served
    late.  (A request dispatched before expiry completes normally:
    in-flight work is not cancelled.)
  - *retries*: :class:`RetryPolicy` / :class:`RetryingClient` — capped
    exponential backoff with jitter on typed backpressure, read-only
    hedging after the tracker's p99, and writes retried ONLY under a
    request id (a retry without one could double-apply, so the client
    refuses to guess);
  - *graceful drain*: :meth:`ShermanServer.drain` — stop admitting,
    serve everything admitted, push a final covering fsync, stop:
    acked-but-unflushed is impossible by construction;
  - *the auditor*: an attached :class:`~sherman_tpu.audit.Auditor`
    records sampled per-key invocation/response events on the
    completion path and checks the acked history linearizable-per-key
    in the background (violations flight-record + black-box dump; the
    inline cost is self-timed and pinned < 2%).

Knobs (documented in the README knob table): ``SHERMAN_SERVE_WIDTHS``
(the ladder), ``SHERMAN_SERVE_P99_MS`` (per-class targets, e.g. ``50``
or ``read:20,insert:200``), ``SHERMAN_SERVE_QUEUE_OPS`` (admission
capacity), ``SHERMAN_SERVE_GROUP_COMMIT_MS`` (journal group commit for
the attached write-ahead journal), ``SHERMAN_SERVE_WEIGHTS`` (weighted
per-tenant shares, e.g. ``gold:2,free:1``), ``SHERMAN_SERVE_DEDUP``
(per-tenant exactly-once window, requests).

Not promised: cross-request ordering.  Requests are independent — a
read admitted after a write may be served from the pre-write snapshot
(the engine's step-boundary linearization); per-key read-your-write
holds only once the write's future resolved before the read was
submitted.  The auditor checks exactly this model (single-key, no
cross-key claims) — see the :mod:`sherman_tpu.audit` docstring.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading
import time
import weakref
from collections import OrderedDict, deque

import numpy as np

from sherman_tpu import config as C
from sherman_tpu import obs
from sherman_tpu.errors import ConfigError, KeyRangeError, ShermanError, \
    StateError
from sherman_tpu.models.batched import DegradedError
from sherman_tpu.obs import device as DEV
from sherman_tpu.obs import recorder as FR
from sherman_tpu.obs import slo as SLO
from sherman_tpu.replica import QuorumTimeoutError
from sherman_tpu.utils import journal as J
from sherman_tpu.workload.device_prep import make_ingress_step

__all__ = [
    "ServeOverloadError", "DeadlineExceededError", "ServeConfig",
    "ServeFuture", "WidthController", "ShermanServer", "RetryPolicy",
    "RetryingClient", "READ_CLASSES", "WRITE_CLASSES", "OP_CLASSES",
]

READ_CLASSES = ("read", "scan")
WRITE_CLASSES = ("insert", "delete")
OP_CLASSES = READ_CLASSES + WRITE_CLASSES


class ServeOverloadError(ShermanError, RuntimeError):
    """Typed admission backpressure: the front door refused this request
    at submit time — queue full, tenant over its fair share, or write
    shed under brownout.  Sits beside the engine's ``ST_LOCK_TIMEOUT``
    and :class:`~sherman_tpu.models.batched.DegradedError` typed
    rejects; clients back off and retry, they never see a silent
    drop."""


class DeadlineExceededError(ShermanError, RuntimeError):
    """Typed deadline shed: the request's budget expired while it was
    still QUEUED, so it was removed before dispatch — a deadline the
    front door cannot meet is reported, never silently served late.
    (Requests already dispatched when the budget expires complete
    normally; in-flight device work is not cancelled.)"""


# ---------------------------------------------------------------------------
# Config
# ---------------------------------------------------------------------------


def _env_weights() -> dict:
    """``SHERMAN_SERVE_WEIGHTS``: weighted per-tenant admission shares,
    ``tenant:weight`` pairs (``gold:2,free:1``).  Unlisted tenants
    weigh 1.0 — the max-min fair share generalizes to weighted max-min
    (a 2:1 split holds 2/3 vs 1/3 of the queue under contention)."""
    v = os.environ.get("SHERMAN_SERVE_WEIGHTS", "")
    out: dict[str, float] = {}
    if not v.strip():
        return out
    try:
        for part in v.split(","):
            name, w = part.split(":")
            out[name.strip()] = float(w)
    except ValueError:
        raise ConfigError(
            f"SHERMAN_SERVE_WEIGHTS={v!r}: want tenant:weight pairs")
    for name, w in out.items():
        if w <= 0:
            raise ConfigError(
                f"SHERMAN_SERVE_WEIGHTS tenant {name!r}: want a "
                "positive weight")
    return out

def _env_widths() -> tuple[int, ...]:
    """``SHERMAN_SERVE_WIDTHS``: comma-separated step-width ladder of
    the front door's read path (ascending; every rung is compiled and
    warmed before the loop seals).  Default suits the CPU mesh; chip
    deployments ladder toward the bench's 4 M-op width."""
    v = os.environ.get("SHERMAN_SERVE_WIDTHS", "1024,4096,16384,65536")
    try:
        widths = tuple(sorted({int(w) for w in v.split(",") if w.strip()}))
    except ValueError:
        raise ConfigError(
            f"SHERMAN_SERVE_WIDTHS={v!r}: want comma-separated ints")
    if not widths or widths[0] <= 0:
        raise ConfigError(
            f"SHERMAN_SERVE_WIDTHS={v!r}: want positive widths")
    return widths


def _env_p99_targets() -> dict[str, float]:
    """``SHERMAN_SERVE_P99_MS``: per-class end-to-end p99 targets in
    ms — a bare number applies to every class, or
    ``read:20,insert:200`` per class."""
    v = os.environ.get("SHERMAN_SERVE_P99_MS", "50")
    out: dict[str, float] = {}
    try:
        if ":" in v:
            for part in v.split(","):
                cls, ms = part.split(":")
                out[cls.strip()] = float(ms)
        else:
            out = {cls: float(v) for cls in OP_CLASSES}
    except ValueError:
        raise ConfigError(
            f"SHERMAN_SERVE_P99_MS={v!r}: want a float or "
            "class:float pairs")
    for cls in out:
        if cls not in OP_CLASSES:
            raise ConfigError(
                f"SHERMAN_SERVE_P99_MS class {cls!r}: want one of "
                f"{OP_CLASSES}")
    for cls in OP_CLASSES:
        out.setdefault(cls, 50.0)
    return out


@dataclasses.dataclass
class ServeConfig:
    """Front-door knobs.  ``from_env`` reads the ``SHERMAN_SERVE_*``
    family; tests construct directly."""

    #: read-path step-width ladder (ascending; each rung one compiled
    #: shape, warmed before seal)
    widths: tuple = dataclasses.field(default_factory=_env_widths)
    #: per-class end-to-end p99 targets (ms)
    p99_targets_ms: dict = dataclasses.field(
        default_factory=_env_p99_targets)
    #: admission capacity in queued OPS (not requests); 0 = derive
    #: 4x the widest rung
    max_queue_ops: int = 0
    #: write-shed brownout thresholds as queue-occupancy fractions
    brownout_hi: float = 0.75
    brownout_lo: float = 0.50
    #: write coalescing: dispatch a write batch at this many ops ...
    write_width: int = 16384
    #: ... or when the oldest pending write has lingered this long
    write_linger_ms: float = 2.0
    #: journal group-commit window for the attached write-ahead journal
    #: (``Journal(group_commit_ms=...)``); RPO stays 0 by construction
    group_commit_ms: float = 2.0
    #: serve-tracker sliding window (the published p99's horizon)
    window_s: float = 10.0
    #: "pipelined" keeps one read batch in flight (two-deep, default);
    #: "aligned" completes each batch before the next dispatch
    fusion: str = "pipelined"
    #: second journaled lane (the write-path SLO story): run the write
    #: flush — engine op + journal append + fsync/group-commit window —
    #: on a DEDICATED thread so the read dispatcher never parks behind
    #: a commit.  The journal's single-writer contract holds (all
    #: writes still issue from ONE thread); device steps stay
    #: serialized by the engine's step mutex.  OFF is the shipped
    #: default (standing guardrail: measurement-driven flips) — the
    #: Round-13 CPU A/B measured parity-to-worse on the shared-core
    #: CPU mesh, where the engine-op wall (not the fsync) dominates
    #: and a second Python thread pays the GIL tax; the chip capture
    #: (real fsync stalls, free cores) is queued in BENCHMARKS.md.
    write_lane: bool = False
    #: weighted per-tenant admission shares (tenant -> weight; unlisted
    #: tenants weigh 1.0) — weighted max-min fair share
    tenant_weights: dict = dataclasses.field(default_factory=_env_weights)
    #: exactly-once dedup window per tenant, in write REQUESTS (rids);
    #: 0 disables the contract plane entirely
    dedup_window: int = 4096
    #: quorum acks (``SHERMAN_ACK_QUORUM``): a write ack resolves only
    #: after this many COPIES hold it durably — the primary counts as
    #: one, so K means the primary plus K-1 follower watermarks
    #: covering the write's journal frontier
    #: (``ReplicaGroup.wait_quorum``).  1 = primary durability only,
    #: the shipped default: the quorum path is never entered and the
    #: front door is bit-identical to a build without it.  Needs an
    #: attached group (:meth:`ShermanServer.attach_replica_group`).
    ack_quorum: int = dataclasses.field(default_factory=C.ack_quorum)
    #: bounded quorum wait per flushed write lane; expiry fails the
    #: lane's futures with the typed ``QuorumTimeoutError`` (the rid
    #: is already in the dedup window, so a retry re-acks)
    quorum_timeout_ms: float = 5000.0
    #: p99 model: est_p99(W) = model_mult x measured wall(W) (formation
    #: wait + service; the open-loop 1.5x-span model plus slack)
    model_mult: float = 2.0
    #: closed-loop steps per ladder rung during calibration
    calib_steps: int = 3
    #: seal the compile ledger after warmup (the zero-retrace contract)
    seal: bool = True

    def __post_init__(self):
        self.widths = tuple(sorted(int(w) for w in self.widths))
        if not self.widths or self.widths[0] <= 0:
            raise ConfigError("ServeConfig.widths: want positive rungs")
        if self.max_queue_ops <= 0:
            self.max_queue_ops = 4 * self.widths[-1]
        if not (0.0 < self.brownout_lo <= self.brownout_hi <= 1.0):
            raise ConfigError(
                "ServeConfig brownout: want 0 < lo <= hi <= 1")
        if self.fusion not in ("aligned", "pipelined"):
            raise ConfigError(
                f"ServeConfig.fusion={self.fusion!r}: want "
                "aligned|pipelined")
        if int(self.ack_quorum) < 1:
            raise ConfigError(
                f"ServeConfig.ack_quorum={self.ack_quorum}: want a "
                "copy count >= 1 (1 = primary durability only)")
        if self.quorum_timeout_ms <= 0:
            raise ConfigError(
                f"ServeConfig.quorum_timeout_ms="
                f"{self.quorum_timeout_ms}: want > 0")

    @classmethod
    def from_env(cls, **overrides) -> "ServeConfig":
        gc = os.environ.get("SHERMAN_SERVE_GROUP_COMMIT_MS")
        q = os.environ.get("SHERMAN_SERVE_QUEUE_OPS")
        wl = os.environ.get("SHERMAN_SERVE_WRITE_LANE")
        dd = os.environ.get("SHERMAN_SERVE_DEDUP")
        kw: dict = {}
        if gc is not None:
            kw["group_commit_ms"] = float(gc)
        if q is not None:
            kw["max_queue_ops"] = int(q)
        if wl is not None:
            kw["write_lane"] = wl.strip().lower() not in (
                "", "0", "false", "off", "no")
        if dd is not None:
            kw["dedup_window"] = int(dd)
        kw.update(overrides)
        return cls(**kw)


# ---------------------------------------------------------------------------
# Futures + requests
# ---------------------------------------------------------------------------

class ServeFuture:
    """Completion handle for one submitted request.  ``result()``
    blocks until the ack and re-raises the typed error when the
    request failed in flight (degraded write shed, deadline shed,
    dispatcher failure).  ``deduped`` marks a result re-acked from the
    exactly-once window (the original ack, not a re-apply)."""

    __slots__ = ("op", "tenant", "n_ops", "t_submit", "rid", "deadline",
                 "deduped", "_ev", "_result", "_error")

    def __init__(self, op: str, tenant: str, n_ops: int,
                 rid=None, deadline: float | None = None):
        self.op = op
        self.tenant = tenant
        self.n_ops = n_ops
        self.t_submit = time.perf_counter()
        self.rid = rid
        self.deadline = deadline
        self.deduped = False
        self._ev = threading.Event()
        self._result = None
        self._error: BaseException | None = None

    def done(self) -> bool:
        return self._ev.is_set()

    def result(self, timeout: float | None = None):
        if not self._ev.wait(timeout):
            raise StateError("serve request still in flight")
        if self._error is not None:
            raise self._error
        return self._result

    def _set(self, result) -> None:
        self._result = result
        self._ev.set()

    def _fail(self, err: BaseException) -> None:
        self._error = err
        self._ev.set()


class _Request:
    __slots__ = ("fut", "keys", "values", "ranges", "payloads",
                 "resolve_payloads")

    def __init__(self, fut, keys=None, values=None, ranges=None,
                 payloads=None, resolve_payloads=False):
        self.fut = fut
        self.keys = keys
        self.values = values
        self.ranges = ranges
        self.payloads = payloads
        self.resolve_payloads = resolve_payloads


# ---------------------------------------------------------------------------
# Width controller
# ---------------------------------------------------------------------------

class WidthController:
    """SLO-adaptive step-width selection over a fixed ladder.

    State per rung: an EWMA of measured step walls (seeded by the
    calibration sweep, refined by every completed step).  The model
    ``est_p99(W) = model_mult * wall(W)`` is the open-loop
    formation-wait + service shape (latency_bench's 1.5x-span p50
    model, with slack for the tail); the pick is

    - the LARGEST rung whose modeled p99 meets the target (throughput
      inside the SLO), clamped by ``cap`` (below),
    - but never a rung wider than the backlog needs — serving 500
      queued ops through a 65 K-wide program pays the wide program's
      wall for nothing (descent cost is per ROW of the compiled
      shape), so the smallest feasible rung covering the backlog wins
      when the queue is shallow.

    ``note_window_p99`` is the measured-truth override: when the
    tracker's observed window p99 breaches the target, the cap steps
    DOWN one rung (multiplicative decrease) and holds for
    ``hold_steps`` completions before probing back up — the model
    proposes, the measurement disposes.
    """

    def __init__(self, widths, target_p99_ms: float, *,
                 model_mult: float = 2.0, ewma: float = 0.3,
                 hold_steps: int = 50):
        self.widths = tuple(sorted(int(w) for w in widths))
        if not self.widths:
            raise ConfigError("WidthController: empty width ladder")
        self.target_p99_ms = float(target_p99_ms)
        self.model_mult = float(model_mult)
        self.ewma = float(ewma)
        self.hold_steps = int(hold_steps)
        self.wall_ms: dict[int, float | None] = {w: None
                                                 for w in self.widths}
        self.cap_idx = len(self.widths) - 1
        self._hold = 0
        self._last = self.widths[0]
        self.picks: dict[int, int] = {w: 0 for w in self.widths}
        self.downshifts = 0

    def seed(self, width: int, wall_ms: float) -> None:
        self.wall_ms[width] = float(wall_ms)

    def update(self, width: int, wall_ms: float) -> None:
        prev = self.wall_ms.get(width)
        self.wall_ms[width] = (float(wall_ms) if prev is None else
                               (1 - self.ewma) * prev
                               + self.ewma * float(wall_ms))
        if self._hold > 0:
            self._hold -= 1
            if self._hold == 0 and self.cap_idx < len(self.widths) - 1:
                self.cap_idx += 1  # probe back up, one rung at a time

    def est_p99_ms(self, width: int) -> float | None:
        w = self.wall_ms.get(width)
        return None if w is None else self.model_mult * w

    def note_window_p99(self, p99_ms: float, *,
                        queue_dominated: bool = False) -> None:
        """Feed the MEASURED window p99 (serve tracker / slo_window);
        a SERVICE-dominated breach steps the cap down one rung and
        holds.  ``queue_dominated`` breaches (batch-formation wait
        exceeds the service wall — the offered load outruns capacity)
        must NOT downshift: a narrower step lowers throughput and
        deepens the very queue that caused the breach; overload relief
        is admission control's job (typed rejects), the width's job is
        to keep the SERVICE share of the latency inside the target."""
        if p99_ms > self.target_p99_ms and not queue_dominated \
                and self.cap_idx > 0 and self._hold == 0:
            self.cap_idx -= 1
            self._hold = self.hold_steps
            self.downshifts += 1

    def feasible(self) -> list[int]:
        out = []
        for w in self.widths[: self.cap_idx + 1]:
            est = self.est_p99_ms(w)
            if est is not None and est <= self.target_p99_ms:
                out.append(w)
        return out

    def pick(self, backlog_ops: int, min_ops: int = 0) -> int:
        """Choose a rung for a step serving ``backlog_ops`` of queued
        work whose largest indivisible request is ``min_ops`` wide.
        Requests never split across steps, so rungs below ``min_ops``
        are structurally unusable — when no rung inside the target can
        carry the head request, the narrowest rung that CAN wins over
        never serving it (its latency is then the queue's honest
        cost, visible in the tracker)."""
        usable = [w for w in self.widths if w >= min_ops] \
            or [self.widths[-1]]
        feas = [w for w in self.feasible() if w >= min_ops]
        if not feas:
            if backlog_ops > usable[0] and self._last in usable:
                # OVERLOAD STABILITY: a deep queue with no rung inside
                # the target means the tail is lost either way — hold
                # the current width instead of collapsing to the
                # narrowest rung, whose lower drain rate would deepen
                # the queue further (the cap-512 death spiral)
                w = self._last
            else:
                # idle/unmeasured: the narrowest structurally-usable
                # rung — lowest latency, and the measured path keeps
                # it honest
                w = usable[0]
        else:
            w = feas[-1]
            for cand in feas:
                if cand >= backlog_ops:
                    w = cand
                    break
        self.picks[w] += 1
        self._last = w
        return w

    def settled_width(self) -> int:
        """The rung this controller has used most — the receipt's
        'settled on' width."""
        return max(self.picks.items(), key=lambda kv: kv[1])[0]

    def snapshot(self) -> dict:
        return {
            "target_p99_ms": self.target_p99_ms,
            "wall_ms": {w: (round(v, 3) if v is not None else None)
                        for w, v in self.wall_ms.items()},
            "cap_width": self.widths[self.cap_idx],
            "picks": dict(self.picks),
            "downshifts": self.downshifts,
            "settled_width": self.settled_width(),
        }


# ---------------------------------------------------------------------------
# The server
# ---------------------------------------------------------------------------

class _TenantState:
    __slots__ = ("queues", "queued_ops", "admitted_ops", "served_ops",
                 "rejected_overload", "rejected_degraded", "weight",
                 "reserve", "dedup", "pending", "dedup_hits",
                 "deadline_shed")

    def __init__(self, weight: float = 1.0, reserve: float = 2.0):
        self.queues = {cls: deque() for cls in OP_CLASSES}
        self.queued_ops = 0
        self.admitted_ops = 0
        self.served_ops = 0
        self.rejected_overload = 0
        self.rejected_degraded = 0
        #: weighted max-min share inputs: this tenant's weight, and the
        #: floor denominator (own weight + the heaviest OTHER tenant's
        #: weight — a lone flooder must always leave a newcomer's share
        #: free, the un-weighted rule's `max(2, active)` generalized)
        self.weight = weight
        self.reserve = reserve
        #: exactly-once plane: acked results keyed by rid (bounded
        #: ring) + in-flight rids (a retry joins the SAME future)
        self.dedup: OrderedDict = OrderedDict()
        self.pending: dict = {}
        self.dedup_hits = 0
        self.deadline_shed = 0


class ShermanServer:
    """The continuous-batching front door over a
    :class:`~sherman_tpu.models.batched.BatchedEngine` (see the module
    docstring for the architecture).

    Lifecycle::

        srv = ShermanServer(eng, config, journal=Journal(...))
        srv.start(calib_keys=some_real_keys)   # warmup + SEAL
        fut = srv.submit("read", keys, tenant="t0")
        vals, found = fut.result()
        srv.stop()                             # drain + unseal

    Single-dispatcher contract: one thread drives every engine step
    (the journaled engine's record-order == apply-order contract);
    ``submit`` is safe from any number of client threads.
    """

    def __init__(self, eng, config: ServeConfig | None = None, *,
                 journal=None, value_heap=None, auditor=None,
                 host_id: int | None = None):
        self.eng = eng
        self.cfg = config or ServeConfig.from_env()
        #: this server's position in the multihost service plane
        #: (PR 19): its stats/receipts carry the host tag so the merged
        #: logical-SLO view (``multihost.merge_host_stats``) can
        #: attribute; ``None`` (the default) = no plane — stats stay
        #: byte-identical to pre-plane builds
        self.host_id = host_id
        #: optional sampling history auditor (sherman_tpu/audit.py):
        #: fed on the completion paths, checked in the background
        self.auditor = auditor
        if eng.router is None:
            raise ConfigError("ShermanServer: attach_router() first")
        self.journal = journal
        if journal is not None:
            eng.attach_journal(journal)
        self.leaf_cache = eng.leaf_cache
        # variable-size records (models/value_heap.py): payload-bearing
        # inserts allocate slabs + install handles; reads submitted with
        # resolve_payloads gather them behind the same ingress step
        self.value_heap = value_heap if value_heap is not None \
            else getattr(eng, "value_heap", None)
        # one ingress step per ladder rung — every compiled shape the
        # sealed loop can dispatch exists up front
        self._steps = {w: make_ingress_step(eng, width=w,
                                            leaf_cache=self.leaf_cache)
                       for w in self.cfg.widths}
        self.controller = WidthController(
            self.cfg.widths, self.cfg.p99_targets_ms["read"],
            model_mult=self.cfg.model_mult)
        self.tracker = SLO.SloTracker(window_s=self.cfg.window_s)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._tenants: dict[str, _TenantState] = {}
        self._rr: deque[str] = deque()  # round-robin tenant order
        self._queued_ops = 0
        self._queued_write_ops = 0
        self._queued_read_ops = 0
        # queue-vs-service latency attribution of the last completed
        # steps (EWMA of formation-wait / service-wall ratio): the
        # controller's breach handler needs to know WHO owns the tail
        self._qwait_ratio = 0.0
        self._running = False
        self._draining = False
        self._thread: threading.Thread | None = None
        self._wthread: threading.Thread | None = None
        self._sealed = False
        self._retrace0 = 0
        self._brownout = False
        self._was_degraded = False
        self._depth = 2 if self.cfg.fusion == "pipelined" else 1
        self._cur_width = self.cfg.widths[0]
        self._completions = 0
        self._last_complete_t = 0.0
        # receipt counters (plain adds on the hot paths — SL006)
        self.admitted_ops = 0
        self.served_ops = 0
        self.acked_writes = 0  # write REQUESTS acked (after the fsync)
        self.rejected_overload = 0
        self.rejected_degraded = 0
        self.dispatch_errors = 0
        # client-contract counters
        self.dedup_hits = 0        # retries re-acked from the window
        self.deadline_shed = 0     # queued requests shed typed at expiry
        self.duplicate_applies = 0  # window misses that re-applied an
        # already-acked rid (the exactly-once invariant: must stay 0 —
        # both guards would have to fail for it to move)
        # quorum-ack counters (PR 18; all zero with ack_quorum=1)
        self.quorum_acks = 0        # write lanes released by a quorum
        self.quorum_timeouts = 0    # bounded waits that expired typed
        self.quorum_wait_ms = 0.0   # summed quorum wait
        self.replica_group = None   # ReplicaGroup quorum waits ride
        self.calibration: dict[int, dict] = {}
        ref = weakref.ref(self)

        def _collect():
            s = ref()
            return s._collect() if s is not None else {}

        obs.register_collector("serve", _collect)

    # -- hot accounting (registered SL006 scopes: plain adds only) -----------

    def _note_admit(self, st: _TenantState, n: int) -> None:
        st.queued_ops += n
        st.admitted_ops += n
        self._queued_ops += n
        self.admitted_ops += n

    def _note_reject_overload(self, st: _TenantState) -> None:
        st.rejected_overload += 1
        self.rejected_overload += 1

    def _note_reject_degraded(self, st: _TenantState) -> None:
        st.rejected_degraded += 1
        self.rejected_degraded += 1

    def _note_served(self, st: _TenantState, n: int) -> None:
        st.served_ops += n
        self.served_ops += n

    def _note_dedup_hit(self, st: _TenantState) -> None:
        st.dedup_hits += 1
        self.dedup_hits += 1

    def _note_deadline_shed(self, st: _TenantState) -> None:
        st.deadline_shed += 1
        self.deadline_shed += 1

    def _note_quorum(self, ms: float) -> None:
        self.quorum_acks += 1
        self.quorum_wait_ms += ms

    # -- admission -----------------------------------------------------------

    def _tenant(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            w = float(self.cfg.tenant_weights.get(tenant, 1.0))
            others = [float(v) for k, v in self.cfg.tenant_weights.items()
                      if k != tenant]
            st = _TenantState(weight=w, reserve=w + max(others + [1.0]))
            self._tenants[tenant] = st
            self._rr.append(tenant)
        return st

    def submit(self, op: str, keys=None, values=None, *,
               tenant: str = "default", ranges=None, payloads=None,
               resolve_payloads: bool = False, rid=None,
               deadline_ms: float | None = None) -> ServeFuture:
        """Admit one request (typed backpressure; see the module
        docstring).  ``keys`` uint64 for read/insert/delete (+
        ``values`` for insert); ``ranges`` [(lo, hi), ...] for scan.
        Returns a :class:`ServeFuture` whose ``result()`` is
        ``(values, found)`` for reads, an ok-per-key bool array for
        inserts, a found-per-key bool array for deletes, and
        ``range_query_many``'s list for scans.

        Variable-size records (value heap attached): an insert with
        ``payloads`` (list of bytes, one per key) allocates heap slabs
        and installs handles; a read with ``resolve_payloads=True``
        resolves its answers' handles behind the same ingress step and
        its ``result()`` is ``(payloads list[bytes|None], found)``; a
        scan with ``resolve_payloads=True`` returns
        ``[(keys, payloads)]`` per range.

        Client contract: ``rid`` (a client-assigned u64 request id on a
        WRITE) arms exactly-once — an already-acked rid returns a
        resolved future carrying the ORIGINAL result (``fut.deduped``),
        an in-flight rid returns the same future, and the dedup check
        runs BEFORE every backpressure gate (a retrying client must be
        able to learn its write landed even under brownout/degraded).
        ``deadline_ms`` attaches a budget; a request still queued past
        it fails typed with :class:`DeadlineExceededError` instead of
        being served late."""
        if op not in OP_CLASSES:
            raise ConfigError(f"submit op {op!r}: want one of "
                              f"{OP_CLASSES}")
        if (payloads is not None or resolve_payloads) \
                and self.value_heap is None:
            raise ConfigError(
                "variable-size records need a value heap "
                "(ShermanServer(..., value_heap=) / "
                "eng.attach_value_heap(); SHERMAN_VALUE_HEAP)")
        if payloads is not None and op != "insert":
            raise ConfigError("payloads only ride insert requests")
        if not self._running:
            raise StateError("server not running (call start())")
        if op == "scan":
            if not ranges:
                raise ConfigError("scan submit needs ranges")
            n = len(ranges)
            if n > self.cfg.widths[-1]:
                raise ConfigError(
                    f"scan of {n} ranges exceeds the flush budget "
                    f"{self.cfg.widths[-1]}; chunk client-side")
        else:
            keys = np.ascontiguousarray(keys, np.uint64)
            n = int(keys.size)
            if n == 0:
                raise ConfigError("empty request")
            # per-class admit cap = the LARGEST batch the class's
            # flush path can actually take (admitting a request no
            # dispatcher budget can pop would hang its future forever
            # at the head of the tenant's FIFO)
            cap = self.cfg.write_width if op in WRITE_CLASSES \
                else self.cfg.widths[-1]
            if n > cap:
                raise ConfigError(
                    f"{op} request of {n} ops exceeds the "
                    f"{cap}-op dispatch budget; chunk client-side")
            if int(keys.min()) < C.KEY_MIN or int(keys.max()) > C.KEY_MAX:
                raise KeyRangeError("keys outside [KEY_MIN, KEY_MAX]")
            if op == "insert":
                if payloads is not None:
                    if len(payloads) != n:
                        raise ConfigError(
                            "insert needs one payload per key")
                    payloads = [bytes(b) for b in payloads]
                    # size-class validation at the DOOR: an oversized
                    # record must reject THIS request typed, not fail
                    # every co-batched tenant's insert at flush time
                    from sherman_tpu.models.value_heap import \
                        class_for_bytes
                    for b in payloads:
                        class_for_bytes(len(b))  # raises ConfigError
                else:
                    values = np.ascontiguousarray(values, np.uint64)
                    if values.shape != keys.shape:
                        raise ConfigError(
                            "insert needs one value per key")
        if deadline_ms is not None and deadline_ms <= 0:
            raise ConfigError(
                f"deadline_ms={deadline_ms}: want a positive budget")
        if rid is not None:
            rid = int(rid)
        deadline = (time.perf_counter() + deadline_ms / 1e3
                    if deadline_ms is not None else None)
        fut = ServeFuture(op, tenant, n, rid=rid, deadline=deadline)
        with self._lock:
            if not self._running:
                # re-check under the lock: a stop() racing the
                # unlocked fast-path check above may already have run
                # its final _fail_queued — a request appended now
                # would never be served OR failed
                raise StateError("server not running (call start())")
            st = self._tenant(tenant)
            # exactly-once: the dedup check runs BEFORE every
            # backpressure gate — a retry of an acked write must learn
            # its result even when a fresh write would be shed
            if rid is not None and op in WRITE_CLASSES \
                    and self.cfg.dedup_window > 0:
                cached = st.dedup.get(rid)
                if cached is not None:
                    self._note_dedup_hit(st)
                    fut.deduped = True
                    fut._set(np.array(cached[1]))
                    return fut
                pend = st.pending.get(rid)
                if pend is not None:
                    # the original is still in flight: the retry joins
                    # it (one apply, one ack, shared by both callers)
                    self._note_dedup_hit(st)
                    return pend
            if op in WRITE_CLASSES:
                reason = self.eng.degraded_reason
                if reason is not None:
                    # degraded brownout: writes reject typed at the
                    # DOOR (fail fast — queueing a write the engine
                    # will refuse only adds latency to the refusal)
                    self._note_reject_degraded(st)
                    raise DegradedError(reason)
                if self._brownout:
                    self._note_reject_overload(st)
                    raise ServeOverloadError(
                        "write shed (brownout): queue at "
                        f"{self._queued_ops}/{self.cfg.max_queue_ops} "
                        "ops; retry with backoff")
            # WEIGHTED max-min fair share: a tenant may hold at most
            # capacity * w / W queued ops, W = the total weight of
            # active tenants (so a greedy tenant saturates its own
            # share and gets typed rejects while polite tenants keep
            # admitting into theirs, proportionally to their weights).
            # The denominator floors at this tenant's weight + the
            # heaviest other's (st.reserve) — a lone flooder must never
            # hold the WHOLE queue, or a newcomer's first request
            # bounces off the total cap before fair sharing can even
            # engage (the un-weighted rule's `max(2, active)`,
            # generalized; identical shares when every weight is 1)
            active_w = sum(t.weight for t in self._tenants.values()
                           if t.queued_ops > 0)
            if st.queued_ops == 0:
                active_w += st.weight
            share = max(1, int(self.cfg.max_queue_ops * st.weight
                               / max(st.reserve, active_w)))
            if self._queued_ops + n > self.cfg.max_queue_ops \
                    or st.queued_ops + n > share:
                self._note_reject_overload(st)
                raise ServeOverloadError(
                    f"queue full (tenant {tenant!r}: "
                    f"{st.queued_ops}+{n} of fair share {share} "
                    f"at weight {st.weight}; "
                    f"total {self._queued_ops}/"
                    f"{self.cfg.max_queue_ops} ops)")
            st.queues[op].append(
                _Request(fut, keys=keys, values=values, ranges=ranges,
                         payloads=payloads,
                         resolve_payloads=resolve_payloads))
            if rid is not None and op in WRITE_CLASSES \
                    and self.cfg.dedup_window > 0:
                st.pending[rid] = fut
            self._note_admit(st, n)
            if op in WRITE_CLASSES:
                self._queued_write_ops += n
            elif op == "read":
                self._queued_read_ops += n
            # write-shed brownout entry (checked on the admission path
            # so pressure reacts at wire speed; exit is checked on the
            # dispatch path as the queue drains)
            if not self._brownout and self._queued_ops \
                    > self.cfg.brownout_hi * self.cfg.max_queue_ops:
                self._brownout = True
                FR.record_event("serve.brownout_enter",
                                queued_ops=self._queued_ops,
                                cap=self.cfg.max_queue_ops)
            self._cv.notify()
        return fut

    # -- lifecycle -----------------------------------------------------------

    def start(self, calib_keys=None, *, calib_writes=None,
              calib_delete_keys=None) -> dict:
        """Warm + calibrate every ladder rung, SEAL the compile ledger,
        and start the dispatcher.

        ``calib_keys`` (uint64, real/loaded keys) drives the read-path
        calibration sweep — closed-loop walls per rung seed the width
        controller and are returned (and kept as ``self.calibration``)
        as the ``{width: {wall_ms, ops_s}}`` frontier receipt.
        ``calib_writes`` (keys, values — value-preserving pairs, e.g.
        the loaded values) warms the insert path; ``calib_delete_keys``
        (keys known ABSENT) warms the delete descent without mutating.
        Skipping calibration (all None) skips the seal too: an unwarmed
        loop would count its own first-dispatch compiles as
        retraces."""
        if self._running:
            raise StateError("server already running")
        if int(self.cfg.ack_quorum) > 1 and self.replica_group is None:
            raise ConfigError(
                f"ack_quorum={self.cfg.ack_quorum} promises "
                "multi-copy durability but no replica group is "
                "attached (attach_replica_group) — acking K copies "
                "without K-1 followers would be a lie")
        ledger = DEV.get_ledger()
        FR.record_event("serve.start", widths=list(self.cfg.widths),
                        fusion=self.cfg.fusion)
        if calib_keys is not None:
            self._calibrate(np.ascontiguousarray(calib_keys, np.uint64),
                            calib_writes, calib_delete_keys)
        self._retrace0 = ledger.retraces
        if calib_keys is not None and self.cfg.seal:
            ledger.seal()
            self._sealed = True
            FR.record_event(
                "serve.sealed",
                walls={str(w): round(c["wall_ms"], 3)
                       for w, c in self.calibration.items()})
        self._running = True
        self._draining = False
        if self.auditor is not None:
            self.auditor.start()
        self._thread = threading.Thread(target=self._loop,
                                        name="sherman-serve",
                                        daemon=True)
        self._thread.start()
        if self.cfg.write_lane:
            # the second journaled lane: write flushes (engine op +
            # journal fsync) run here so the read dispatcher never
            # stalls behind a commit window (the YCSB-A read-p99 story)
            self._wthread = threading.Thread(target=self._write_loop,
                                             name="sherman-serve-write",
                                             daemon=True)
            self._wthread.start()
        return dict(self.calibration)

    def _calibrate(self, keys_pool, calib_writes, calib_delete_keys):
        """Closed-loop sweep over the ladder: compile + warm every
        rung's programs (ingress serve, cache probe, straggler rescue,
        write paths) and measure each rung's pipelined wall — the
        width x latency frontier seed."""
        rng = np.random.default_rng(17)
        K = max(1, self.cfg.calib_steps)
        for w, step in self._steps.items():
            kidx = rng.integers(0, keys_pool.size, (K + 1, w))
            # warm (compile) outside the timing, then a short two-deep
            # closed loop — the pipelined wall the serving loop pays
            step(keys_pool[kidx[0]])
            t0 = time.perf_counter()
            h = step.dispatch(keys_pool[kidx[1]])
            for i in range(1, K):
                h2 = step.dispatch(keys_pool[kidx[i + 1]])
                step.complete(h)
                h = h2
            step.complete(h)
            wall_ms = (time.perf_counter() - t0) / K * 1e3
            self.controller.seed(w, wall_ms)
            self.calibration[w] = {
                "wall_ms": wall_ms,
                "ops_s": w / (wall_ms / 1e3),
            }
        # straggler rescue path (root descent at the engine width)
        self.eng.search(keys_pool[rng.integers(0, keys_pool.size, 64)])
        # value-heap resolve programs: warm the width-bucket ladder the
        # payload reads can dispatch (pow2 node multiples up to the
        # widest rung) plus the put/free write paths, twice each for
        # the threaded-carry variants — a payload read mid-window must
        # not be the resolve program's first compile
        if self.value_heap is not None:
            vh = self.value_heap
            wmax = self.cfg.widths[-1]
            w = 256 * vh.N
            probe = keys_pool[rng.integers(0, keys_pool.size, 8)]
            pv, pf = self.eng.search(probe)
            while True:
                pad = np.zeros(w, np.uint64)
                pad[: probe.size] = pv
                fnd = np.zeros(w, bool)
                fnd[: probe.size] = pf
                vh.resolve_u64(pad[:w], fnd[:w])
                vh.resolve_u64(pad[:w], fnd[:w])
                if w >= wmax:
                    break
                w *= 2
            wk = np.unique(keys_pool[rng.integers(0, keys_pool.size, 32)])
            try:
                # value-preserving warm: read the payloads back and
                # re-put them (compiles the slab-scatter + insert
                # shapes without changing a record)
                pays, pfound = vh.get(wk)
                keep = [p if p is not None else b"\x00" for p in pays]
                vh.put(wk, keep)
                vh.put(wk, keep)
            except ShermanError as e:
                # a tree whose values were never migrated to handles
                # cannot warm the payload write path — serve it, but
                # payload classes stay cold (first dispatch compiles)
                FR.record_event("serve.heap_warm_skipped", error=repr(e))
        # scan path (range_query_many compiles its leaf-walk lazily;
        # twice for the threaded-carry variant, like the writes below)
        lo = int(keys_pool.min())
        self.eng.range_query_many([(lo, lo + 64)])
        self.eng.range_query_many([(lo, lo + 64)])
        # sketch-admission fill program (a fill mid-window must not be
        # the first compile of engine.cache_fill)
        if self.leaf_cache is not None and self.leaf_cache.admit_every:
            seed_keys = self.leaf_cache.cached_keys()
            if seed_keys.size == 0:
                seed_keys = np.unique(keys_pool[rng.integers(
                    0, keys_pool.size, 256)])
            self.leaf_cache.fill(seed_keys)
        # write paths warm TWICE: the first call's program outputs
        # (pool/counters/dirty) become the second call's inputs, and
        # host-staged vs threaded avals are DISTINCT jit cache entries
        # (bench.py's second-warmup-step lesson) — a single warmup
        # would leave the threaded variant to compile inside the
        # sealed window as a false retrace
        if calib_writes is not None:
            wk, wv = calib_writes
            wk = np.ascontiguousarray(wk, np.uint64)
            wv = np.ascontiguousarray(wv, np.uint64)
            self.eng.insert(wk, wv)
            self.eng.insert(wk, wv)
        if calib_delete_keys is not None:
            dk = np.ascontiguousarray(calib_delete_keys, np.uint64)
            self.eng.delete(dk)
            self.eng.delete(dk)

    def stop(self, drain: bool = True, timeout: float = 60.0) -> None:
        """Stop serving.  ``drain=True`` serves everything already
        admitted first; ``drain=False`` fails queued requests with the
        typed :class:`~sherman_tpu.errors.StateError` (the crash-drill
        shape keeps the journal UNCLOSED — durable records need no
        goodbye)."""
        with self._lock:
            if not self._running:
                return
            self._running = False
            self._draining = bool(drain)
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        if self._wthread is not None:
            self._wthread.join(timeout)
        if self.auditor is not None:
            self.auditor.stop()  # final drain-all checker tick
        if self._sealed:
            DEV.get_ledger().unseal()
            self._sealed = False
        FR.record_event("serve.stop", served_ops=self.served_ops,
                        acked_writes=self.acked_writes)

    def drain(self, timeout: float = 60.0) -> None:
        """Graceful drain: stop admitting, serve everything already
        admitted (futures resolve or fail typed), push one final
        covering fsync on the attached journal, then stop.  After
        ``drain()`` returns, acked-but-unflushed is impossible by
        construction: every write ack already gated on a covering
        fsync, and the epilogue fsync closes the ``sync=False``
        window too."""
        self.stop(drain=True, timeout=timeout)
        jrn = self.journal if self.journal is not None \
            else getattr(self.eng, "journal", None)
        if jrn is not None:
            jrn.sync_now()  # no-op on a closed journal (its own guard)
        FR.record_event("serve.drain", served_ops=self.served_ops,
                        acked_writes=self.acked_writes)

    def kill(self) -> None:
        """Crash-drill stop: abandon the dispatcher without draining
        and WITHOUT closing the journal — exactly what a process crash
        leaves behind.  Every acked write is already covered by an
        fsync (the ack gate), so recovery replays to RPO 0."""
        self.stop(drain=False, timeout=5.0)

    def attach_auditor(self, auditor) -> None:
        """Attach (or detach, with None) the sampling history auditor;
        started/stopped with the server when attached before
        :meth:`start`."""
        self.auditor = auditor

    def attach_replica_group(self, group) -> None:
        """Attach (or detach, with None) the replica group whose
        follower watermarks quorum acks resolve against
        (``cfg.ack_quorum`` > 1).  With the default ``ack_quorum=1``
        an attached group is ignored by the write path entirely."""
        self.replica_group = group

    def _quorum_gate(self) -> None:
        """The quorum-ack gate: with ``ack_quorum`` K > 1 and a group
        attached, block until K-1 non-quarantined follower watermarks
        COVER the durable journal frontier (captured now — after the
        lane's engine op and ack record returned, so the frontier
        bounds both).  Raises the typed ``QuorumTimeoutError`` at the
        bounded deadline; the lane's rids are already durable in the
        dedup window, so a client retry re-acks exactly-once.  Never
        entered with K=1 (the shipped default): zero added work,
        bit-identical acks."""
        g = self.replica_group
        need = int(self.cfg.ack_quorum) - 1
        if g is None or need <= 0:
            return
        try:
            rc = g.wait_quorum(
                need, timeout_s=self.cfg.quorum_timeout_ms / 1e3)
        except QuorumTimeoutError:
            self.quorum_timeouts += 1
            raise
        self._note_quorum(rc["waited_ms"])

    def seed_dedup(self, window, rejournal: bool = True) -> int:
        """Adopt a recovered exactly-once window
        (``RecoveryPlane.recover``'s ``plane.dedup_window``:
        ``{(tenant, rid): (op_kind, ok array)}``, in ack order) — a
        write retried across the cold crash then re-acks its ORIGINAL
        result instead of re-applying.  ``rejournal`` (default) writes
        the adopted window back into the live journal segment as one
        J_ACK record: recovery re-bases onto a fresh chain (the old
        segments' ack records are swept), so without it a SECOND crash
        would forget the window.  Returns entries adopted."""
        n = 0
        acks = []
        with self._lock:
            for (tenant, rid), entry in window.items():
                # entries are (op, ok) or (op, ok, handles) — heap
                # writes carry payload provenance (PR 16); both the
                # adopted window and the re-journaled record keep it
                opcode, ok = entry[0], entry[1]
                prov = entry[2:] if len(entry) > 2 else ()
                st = self._tenant(tenant)
                st.dedup[int(rid)] = (int(opcode), np.array(ok), *prov)
                st.dedup.move_to_end(int(rid))
                while len(st.dedup) > max(1, self.cfg.dedup_window):
                    st.dedup.popitem(last=False)
                acks.append((int(rid), tenant, int(opcode),
                             np.array(ok), *prov))
                n += 1
        if rejournal and acks:
            jrn = self.journal if self.journal is not None \
                else getattr(self.eng, "journal", None)
            if jrn is not None:
                jrn.append_acks(acks)
        return n

    @property
    def retraces(self) -> int:
        """Steady-state retraces observed since this server sealed."""
        return DEV.get_ledger().retraces - self._retrace0

    def retarget(self, op_class: str, p99_ms: float) -> None:
        """Re-aim one class's end-to-end p99 target at runtime (SLOs
        are operator policy, not a rebuild) — the adaptive controller
        follows on its next pick."""
        if op_class not in OP_CLASSES:
            raise ConfigError(f"retarget class {op_class!r}: want one "
                              f"of {OP_CLASSES}")
        self.cfg.p99_targets_ms[op_class] = float(p99_ms)
        if op_class == "read":
            self.controller.target_p99_ms = float(p99_ms)

    # -- dispatcher ----------------------------------------------------------

    def _loop(self) -> None:
        pend: deque = deque()  # in-flight read slots (two-deep pipeline)
        while True:
            with self._lock:
                if not self._running and (not self._draining
                                          or self._queued_ops == 0):
                    break
                has_work = self._queued_ops > 0
                if not has_work and not pend:
                    self._cv.wait(0.002)
                    has_work = self._queued_ops > 0
                    if not has_work and not pend:
                        continue
            try:
                self._check_degraded_transition()
                # write flushes ride the dedicated lane when enabled —
                # the dispatcher's read loop must never stall behind a
                # journal fsync (the PR-13 REMAINING write-path story)
                did = False if self.cfg.write_lane \
                    else self._maybe_flush_writes()
                did = self._maybe_flush_scans() or did
                slot = self._dispatch_reads()
                if slot is not None:
                    pend.append(slot)
                    did = True
                while len(pend) >= (self._depth if slot is not None
                                    else 1):
                    self._complete_read(pend.popleft())
                    did = True
                    if not pend:
                        break
                if not did:
                    # admitted work exists but none of it is due yet
                    # (write linger): sleep a beat instead of spinning
                    # the GIL out from under the client threads
                    with self._lock:
                        self._cv.wait(0.0005)
            except BaseException as e:  # noqa: BLE001 — serving loop
                # must survive a bad batch: the batch's futures carry
                # the error, the loop keeps serving everyone else
                self.dispatch_errors += 1
                FR.record_event("serve.dispatch_error", error=repr(e))
                if isinstance(e, (KeyboardInterrupt, SystemExit)):
                    raise
        # shutdown: drain the pipeline, wait out the write lane (its
        # own drain loop exits on the same flags), then fail the rest.
        # A graceful drain completes in-flight slots with full
        # semantics; a kill() abandons them through the ingress step's
        # drain hook (materialize-and-answer WITHOUT straggler rescue —
        # a crashing teardown must not launch fresh root descents)
        for slot in pend:
            try:
                if self._draining:
                    self._complete_read(slot)
                else:
                    width, reqs, handle, _t0, tok = slot
                    self._fail_batch(reqs, StateError(
                        "server killed with the batch in flight"))
                    self._steps[width].drain(handle)
                    if tok is not None and self.auditor is not None:
                        self.auditor.end_ops(tok)
            except BaseException:  # noqa: BLE001
                pass
        if self._wthread is not None and self._wthread.is_alive() \
                and self._wthread is not threading.current_thread():
            self._wthread.join(10.0)
        self._fail_queued(StateError("server stopped"))

    def _write_loop(self) -> None:
        """The second journaled lane: pops write requests and runs the
        engine op + journal append/fsync off the read dispatcher's hot
        loop.  Single-writer journal contract preserved — every write
        still issues from THIS one thread."""
        while True:
            with self._lock:
                if not self._running and (not self._draining
                                          or self._queued_write_ops == 0):
                    break
                if self._queued_write_ops == 0:
                    self._cv.wait(0.002)
                    continue
            try:
                if not self._maybe_flush_writes():
                    with self._lock:
                        self._cv.wait(0.0005)
            except BaseException as e:  # noqa: BLE001 — the lane must
                # survive a bad batch like the dispatcher does
                self.dispatch_errors += 1
                FR.record_event("serve.dispatch_error", error=repr(e),
                                lane="write")
                if isinstance(e, (KeyboardInterrupt, SystemExit)):
                    raise

    def _fail_queued(self, err: BaseException) -> None:
        with self._lock:
            for st in self._tenants.values():
                for q in st.queues.values():
                    while q:
                        req = q.popleft()
                        n = req.fut.n_ops
                        st.queued_ops -= n
                        self._queued_ops -= n
                        if req.fut.op in WRITE_CLASSES:
                            self._queued_write_ops -= n
                        elif req.fut.op == "read":
                            self._queued_read_ops -= n
                        if req.fut.rid is not None:
                            st.pending.pop(req.fut.rid, None)
                        req.fut._fail(err)

    def _check_degraded_transition(self) -> None:
        deg = self.eng.degraded
        if deg and not self._was_degraded:
            # shed queued writes typed; reads keep serving (the
            # degraded read path is the brownout's whole premise)
            reason = self.eng.degraded_reason or "degraded"
            with self._lock:
                for st in self._tenants.values():
                    for cls in WRITE_CLASSES:
                        q = st.queues[cls]
                        while q:
                            req = q.popleft()
                            n = req.fut.n_ops
                            st.queued_ops -= n
                            self._queued_ops -= n
                            self._queued_write_ops -= n
                            st.rejected_degraded += 1
                            self.rejected_degraded += 1
                            if req.fut.rid is not None:
                                st.pending.pop(req.fut.rid, None)
                            req.fut._fail(DegradedError(reason))
            FR.record_event("serve.brownout_enter", degraded=True,
                            reason=reason)
        elif not deg and self._was_degraded:
            FR.record_event("serve.brownout_exit", degraded=True)
        self._was_degraded = deg

    def _shed_expired(self, st: _TenantState, q, now: float) -> None:
        """Deadline shed at the queue head: a request whose budget
        expired while queued fails typed BEFORE dispatch — the
        contract's 'never silently served late' half.  Runs inside the
        admission lock on the dispatch path (registered SL001 scope:
        plain pops and adds, no device work)."""
        while q and q[0].fut.deadline is not None \
                and q[0].fut.deadline < now:
            req = q.popleft()
            n = req.fut.n_ops
            st.queued_ops -= n
            self._queued_ops -= n
            if req.fut.op in WRITE_CLASSES:
                self._queued_write_ops -= n
            elif req.fut.op == "read":
                self._queued_read_ops -= n
            if req.fut.rid is not None:
                st.pending.pop(req.fut.rid, None)
            self._note_deadline_shed(st)
            req.fut._fail(DeadlineExceededError(
                "deadline expired while queued; shed before dispatch"))

    def _take(self, classes, budget_ops: int) -> list[_Request]:
        """Pop up to ``budget_ops`` ops of the given classes —
        round-robin across tenants (max-min fair service), FIFO within
        a tenant, whole requests only (no mid-request splits).
        Expired heads are deadline-shed typed as they surface."""
        out: list[_Request] = []
        now = time.perf_counter()
        with self._lock:
            if not self._rr:
                return out
            took = budget_ops
            idle_rounds = 0
            while took > 0 and idle_rounds < len(self._rr):
                tenant = self._rr[0]
                self._rr.rotate(-1)
                st = self._tenants[tenant]
                got = False
                for cls in classes:
                    q = st.queues[cls]
                    self._shed_expired(st, q, now)
                    if q and q[0].fut.n_ops <= took:
                        req = q.popleft()
                        n = req.fut.n_ops
                        st.queued_ops -= n
                        self._queued_ops -= n
                        if cls in WRITE_CLASSES:
                            self._queued_write_ops -= n
                        elif cls == "read":
                            self._queued_read_ops -= n
                        took -= n
                        out.append(req)
                        got = True
                        break
                idle_rounds = 0 if got else idle_rounds + 1
            if self._brownout and self._queued_ops \
                    < self.cfg.brownout_lo * self.cfg.max_queue_ops:
                self._brownout = False
                FR.record_event("serve.brownout_exit",
                                queued_ops=self._queued_ops)
        return out

    def _read_backlog(self) -> tuple[int, int]:
        """(queued read ops, widest head-of-queue request) — the
        controller's pick inputs; head size matters because requests
        never split across steps."""
        with self._lock:
            head = 0
            for st in self._tenants.values():
                q = st.queues["read"]
                if q and q[0].fut.n_ops > head:
                    head = q[0].fut.n_ops
            return self._queued_read_ops, head

    def _dispatch_reads(self):
        """Form one read step at the controller's width and launch it
        (async).  Returns the in-flight slot or None."""
        backlog, head = self._read_backlog()
        if backlog == 0:
            return None
        width = self.controller.pick(backlog, head)
        if width != self._cur_width:
            FR.record_event("serve.width_change", frm=self._cur_width,
                            to=width)
            self._cur_width = width
        reqs = self._take(("read",), width)
        if not reqs:
            return None
        keys = np.concatenate([r.keys for r in reqs]) \
            if len(reqs) > 1 else reqs[0].keys
        # auditor intent for the whole flight: a pipelined read records
        # its events a full iteration after dispatch — the checker's
        # cut must not close a window over it meanwhile
        tok = self.auditor.begin_ops(
            min(r.fut.t_submit for r in reqs)) \
            if self.auditor is not None else None
        t0 = time.perf_counter()
        try:
            handle = self._steps[width].dispatch(keys)
        except BaseException as e:  # noqa: BLE001 — the batch's futures
            # must carry the failure; the loop keeps serving
            self._fail_batch(reqs, e)
            if tok is not None:
                self.auditor.end_ops(tok)
            return None
        return (width, reqs, handle, t0, tok)

    def _fail_batch(self, reqs, e: BaseException) -> None:
        self.dispatch_errors += 1
        err = e if isinstance(e, ShermanError) \
            else StateError(f"serve dispatch failed: {e!r}")
        FR.record_event("serve.dispatch_error", error=repr(e))
        for r in reqs:
            if r.fut.rid is not None:
                with self._lock:
                    st = self._tenants.get(r.fut.tenant)
                    if st is not None:
                        st.pending.pop(r.fut.rid, None)
            if not r.fut.done():  # a deduped re-ack already resolved
                r.fut._fail(err)
        if isinstance(e, (KeyboardInterrupt, SystemExit)):
            raise e

    def _complete_read(self, slot) -> None:
        width, reqs, handle, t0, tok = slot
        try:
            self._complete_read_inner(width, reqs, handle, t0)
        finally:
            if tok is not None and self.auditor is not None:
                self.auditor.end_ops(tok)

    def _complete_read_inner(self, width, reqs, handle, t0) -> None:
        try:
            vals, found = self._steps[width].complete(handle)
        except BaseException as e:  # noqa: BLE001
            self._fail_batch(reqs, e)
            return
        t1 = time.perf_counter()
        wall = t1 - t0
        n = vals.shape[0]
        # service-side refinement: the MARGINAL completion interval
        # feeds the controller — under the two-deep pipeline a step's
        # dispatch-to-complete wall includes its predecessor's device
        # time, so attributing the raw wall would double-count the
        # pipeline and talk the controller out of perfectly feasible
        # rungs (est = model x 2 x true service).  The marginal
        # interval is exactly what the closed-loop calibration
        # measured (elapsed / K over an overlapped chain).
        svc = t1 - max(t0, self._last_complete_t)
        self._last_complete_t = t1
        self.controller.update(width, svc * 1e3)
        SLO.observe("read", n, wall)
        # variable-size records: one batched handle-resolve gather for
        # every payload-requesting request in this step (stale handles
        # fall back to the heap's revalidate-and-retry read per slice)
        pay = nb = vok = None
        side = cache = None
        if self.value_heap is not None \
                and any(r.resolve_payloads for r in reqs):
            # payload sidecar (PR 16): positions whose pinned bytes are
            # certified by the LIVE handle (the tree value just read —
            # a rewrite always changes it) skip the resolve gather;
            # with every position pinned the gather is skipped whole
            gather_found = found
            cache = self.eng.leaf_cache
            if cache is not None:
                side, gather_found = self._sidecar_hits(
                    reqs, vals, found, cache)
            try:
                if bool(np.asarray(gather_found).any()):
                    pay, nb, vok = self.value_heap.resolve_u64(
                        vals, gather_found)
            except BaseException as e:  # noqa: BLE001 — every future in
                # the slot must resolve; a hung client is worse than a
                # failed batch
                self._fail_batch(reqs, e)
                return
        off = 0
        oldest = t1
        # auditor feed: u64-register reads only (handle-bearing heap
        # reads are outside the register model — see audit.py)
        aud = self.auditor if self.value_heap is None else None
        for req in reqs:
            m = req.fut.n_ops
            try:
                if req.resolve_payloads:
                    req.fut._set(self._payload_result(
                        req, vals, found, pay, nb, vok, off, m,
                        side=side, cache=cache))
                else:
                    req.fut._set((vals[off:off + m],
                                  found[off:off + m]))
            except BaseException as e:  # noqa: BLE001 — a raising
                # per-request payload resolve (HeapCorruptError on a
                # torn slab) must fail THAT future typed, not leave it
                # (and every later request in the batch) unset forever
                self.dispatch_errors += 1
                FR.record_event("serve.dispatch_error", error=repr(e))
                req.fut._fail(e if isinstance(e, ShermanError)
                              else StateError(
                                  f"payload resolve failed: {e!r}"))
                if isinstance(e, (KeyboardInterrupt, SystemExit)):
                    raise
            # end-to-end (submit -> ack) latency — the SLO the target
            # governs, attributed per REQUEST (the client's unit of
            # experience) weighted by its ops
            self.tracker.observe("read", m, t1 - req.fut.t_submit)
            if aud is not None:
                aud.observe_read(req.keys, vals[off:off + m],
                                 found[off:off + m],
                                 req.fut.t_submit, t1)
            if req.fut.t_submit < oldest:
                oldest = req.fut.t_submit
            st = self._tenants[req.fut.tenant]
            self._note_served(st, m)
            off += m
        # queue-vs-service attribution: formation wait of the batch's
        # OLDEST request vs the service wall — when waiting dominates,
        # the tail belongs to the offered load, not the width
        qwait = max(0.0, t0 - oldest)
        ratio = qwait / wall if wall > 0 else 0.0
        self._qwait_ratio = 0.7 * self._qwait_ratio + 0.3 * ratio
        self._completions += 1
        if self._completions % 16 == 0:
            # measured-truth override: the window p99 disposes what the
            # wall model proposed (queue-dominated breaches excluded —
            # see WidthController.note_window_p99)
            w = self.tracker.window().get("read")
            if w and w["window_ops"]:
                self.controller.note_window_p99(
                    w["p99_ms"],
                    queue_dominated=self._qwait_ratio > 1.0)

    def _sidecar_hits(self, reqs, vals, found, cache):
        """Probe the leaf cache's payload sidecar for every found
        payload position in the slot.  -> (side, gather_found):
        ``side[p]`` holds certified pinned bytes (the pin's handle
        equals the live tree value at ``p``), and those positions are
        masked OUT of the resolve gather — all-hit slots skip the
        fused gather entirely."""
        side = [None] * int(np.asarray(vals).shape[0])
        pk, ph, pp = [], [], []
        off = 0
        for r in reqs:
            m = r.fut.n_ops
            if r.resolve_payloads:
                for j in range(m):
                    if found[off + j]:
                        pk.append(r.keys[j])
                        ph.append(vals[off + j])
                        pp.append(off + j)
            off += m
        if not pk:
            return side, found
        blobs = cache.payload_hits(pk, ph)
        gf = None
        for b, p in zip(blobs, pp):
            if b is not None:
                side[p] = b
                if gf is None:
                    gf = np.array(found)
                gf[p] = False
        return side, (found if gf is None else gf)

    def _payload_result(self, req, vals, found, pay, nb, vok,
                        off: int, m: int, side=None, cache=None):
        """Assemble one payload-read request's result slice from the
        sidecar pins + the batch's resolve gather; stale handles
        revalidate through the heap's bounded-retry read.  Fresh
        gather results are pinned (key + live handle) so the next
        read of the key serves bytes without a gather."""
        vh = self.value_heap
        sl_found = np.array(found[off:off + m])
        out: list = [None] * m
        stale = []
        fresh_k, fresh_h, fresh_b = [], [], []
        for j in range(m):
            if not sl_found[j]:
                continue
            if side is not None and side[off + j] is not None:
                out[j] = side[off + j]
            elif vok is not None and vok[off + j]:
                b = vh._words_to_bytes(pay[off + j], int(nb[off + j]))
                out[j] = b
                if cache is not None:
                    fresh_k.append(req.keys[j])
                    fresh_h.append(vals[off + j])
                    fresh_b.append(b)
            else:
                stale.append(j)
        if stale:
            p2, f2 = vh.get(req.keys[np.asarray(stale)])
            for k, j in enumerate(stale):
                out[j] = p2[k]
                sl_found[j] = bool(f2[k])
        if fresh_k:
            cache.pin_payloads(fresh_k, fresh_h, fresh_b)
        return out, sl_found

    def _write_due(self) -> bool:
        with self._lock:
            if self._queued_write_ops >= self.cfg.write_width:
                return True
            if self._queued_write_ops == 0:
                return False
            if not self._running:  # draining
                return True
            oldest = None
            for st in self._tenants.values():
                for cls in WRITE_CLASSES:
                    q = st.queues[cls]
                    if q:
                        t = q[0].fut.t_submit
                        oldest = t if oldest is None else min(oldest, t)
            return oldest is not None and \
                (time.perf_counter() - oldest) * 1e3 \
                >= self.cfg.write_linger_ms

    def _split_deduped(self, reqs):
        """Dispatch-side exactly-once guard: re-ack any popped request
        whose rid already sits in the window (a retry admitted before
        :meth:`seed_dedup` ran, or a racing duplicate) and return the
        remainder.  Applying such a request would be a duplicate apply
        — the exact bug the contract plane exists to kill — so it is
        counted ``duplicate_applies``-adjacent only if BOTH guards
        miss (which this one makes structurally impossible)."""
        if self.cfg.dedup_window <= 0:
            return reqs
        out = []
        hits = []
        for r in reqs:
            rid = r.fut.rid
            if rid is not None:
                with self._lock:
                    st = self._tenant(r.fut.tenant)
                    cached = st.dedup.get(rid)
                    if cached is not None:
                        self._note_dedup_hit(st)
                        st.pending.pop(rid, None)
                        hits.append((r, cached))
                        continue
            out.append(r)
        if hits:
            # a re-ack honors the same quorum promise as the original
            # ack: the retry path across a QuorumTimeoutError lands
            # HERE, and resolving before coverage would let a K-copy
            # ack outrun its K copies (no-op with ack_quorum=1)
            try:
                self._quorum_gate()
            except QuorumTimeoutError as e:
                for r, _ in hits:
                    r.fut._fail(e)
            else:
                for r, cached in hits:
                    r.fut.deduped = True
                    r.fut._set(np.array(cached[1]))
        return out

    def _ack_batch(self, reqs, results, opcode: int,
                   provenance=None) -> None:
        """Journal + cache a write batch's exactly-once results —
        post-apply, PRE-ack: called before any of the batch's futures
        resolve, under the same durability gate as the engine record
        (one ``J_ACK`` frame covers every rid the flush coalesced; a
        raising append fails the whole batch, so no ack can outrun its
        record).  ``provenance`` (heap writes, PR 16): per-request u64
        handle arrays aligned with ``results`` — journaled into the
        ack entries so a recovered window attests where each acked
        payload lives (slab address + version), not just its bits."""
        if self.cfg.dedup_window <= 0:
            return
        if provenance is None:
            acks = [(r.fut.rid, r.fut.tenant, opcode, res)
                    for r, res in zip(reqs, results)
                    if r.fut.rid is not None]
        else:
            acks = [(r.fut.rid, r.fut.tenant, opcode, res, prov)
                    for r, res, prov in zip(reqs, results, provenance)
                    if r.fut.rid is not None]
        if not acks:
            return
        jrn = self.journal if self.journal is not None \
            else getattr(self.eng, "journal", None)
        if jrn is not None:
            try:
                jrn.append_acks(acks)
            except StateError:
                # a checkpoint rotation swapped the engine's journal
                # between this flush's engine record and its ack
                # record: re-read once and land the acks in the fresh
                # segment (same durability gate)
                jrn2 = self.journal if self.journal is not None \
                    else getattr(self.eng, "journal", None)
                if jrn2 is None or jrn2 is jrn:
                    raise
                jrn2.append_acks(acks)
        with self._lock:
            for i, (r, res) in enumerate(zip(reqs, results)):
                rid = r.fut.rid
                if rid is None:
                    continue
                st = self._tenant(r.fut.tenant)
                st.dedup[rid] = (opcode, np.array(res)) \
                    if provenance is None \
                    else (opcode, np.array(res),
                          np.array(provenance[i]))
                st.dedup.move_to_end(rid)
                while len(st.dedup) > self.cfg.dedup_window:
                    st.dedup.popitem(last=False)
                st.pending.pop(rid, None)

    def _audit_writes(self, op: int, reqs, results, t1: float,
                      with_values: bool) -> None:
        """Feed the attached auditor one completed write batch (sampled
        per-key events; u64-value writes only — payload writes are
        outside the auditor's register model)."""
        aud = self.auditor
        if aud is None:
            return
        for r, res in zip(reqs, results):
            aud.observe_write(op, r.keys, r.fut.t_submit, t1,
                              values=r.values if with_values else None,
                              ok=res if with_values else None)

    def _maybe_flush_writes(self) -> bool:
        if not self._write_due():
            return False
        reqs = self._split_deduped(
            self._take(WRITE_CLASSES, self.cfg.write_width))
        if not reqs:
            return False
        # auditor intent: the flush is about to APPLY writes whose
        # events only land in the ring after the ack (journal fsync in
        # between) — the intent pins the checker's drain cut so reads
        # observing these writes are never judged without them
        tok = self.auditor.begin_ops(
            min(r.fut.t_submit for r in reqs)) \
            if self.auditor is not None else None
        try:
            return self._flush_writes(reqs)
        finally:
            if tok is not None:
                self.auditor.end_ops(tok)

    def _flush_writes(self, reqs) -> bool:
        hins = [r for r in reqs
                if r.fut.op == "insert" and r.payloads is not None]
        ins = [r for r in reqs
               if r.fut.op == "insert" and r.payloads is None]
        dels = [r for r in reqs if r.fut.op == "delete"]
        if hins:
            # variable-size records: heap slab writes + handle installs
            # (journaled pre-ack inside put(), same gate as insert)
            keys = np.concatenate([r.keys for r in hins]) \
                if len(hins) > 1 else hins[0].keys
            payloads = [b for r in hins for b in r.payloads]
            try:
                hst = self.value_heap.put(keys, payloads)
                t1 = time.perf_counter()
                hto = np.asarray(hst["lock_timeout_keys"], np.uint64) \
                    if hst["lock_timeouts"] else None
                results = [np.ones(r.fut.n_ops, bool) if hto is None
                           else ~np.isin(r.keys, hto) for r in hins]
                # payload provenance (PR 16): the handle each acked
                # payload landed at rides the J_ACK entry (0 for keys
                # that timed out or were superseded within the batch)
                hmap = hst.get("handle_map") or {}
                provenance = [np.asarray(
                    [hmap.get(int(k), 0) for k in r.keys], np.uint64)
                    for r in hins]
                self._ack_batch(hins, results, J.J_HEAP_PUT,
                                provenance=provenance)
                self._quorum_gate()
                for r, ok in zip(hins, results):
                    r.fut._set(ok)
                    self.tracker.observe("insert", r.fut.n_ops,
                                         t1 - r.fut.t_submit)
                    self._note_served(self._tenants[r.fut.tenant],
                                      r.fut.n_ops)
                    self.acked_writes += 1
            except BaseException as e:  # noqa: BLE001
                self._fail_batch(hins, e)
        if ins:
            keys = np.concatenate([r.keys for r in ins]) \
                if len(ins) > 1 else ins[0].keys
            values = np.concatenate([r.values for r in ins]) \
                if len(ins) > 1 else ins[0].values
            try:
                # the ack gate: insert() returns only after the journal
                # record covering these rows is DURABLE (fsync'd /
                # group-committed) — resolving the futures after this
                # call is what "journaled by construction" means
                stats = self.eng.insert(keys, values)
                t1 = time.perf_counter()
                to = np.asarray(stats["lock_timeout_keys"], np.uint64) \
                    if stats["lock_timeouts"] else None
                results = [np.ones(r.fut.n_ops, bool) if to is None
                           else ~np.isin(r.keys, to) for r in ins]
                self._ack_batch(ins, results, J.J_UPSERT)
                # quorum acks (PR 18): the futures below resolve only
                # after K-1 followers cover this flush's frontier
                self._quorum_gate()
                for r, ok in zip(ins, results):
                    r.fut._set(ok)
                    self.tracker.observe("insert", r.fut.n_ops,
                                         t1 - r.fut.t_submit)
                    self._note_served(self._tenants[r.fut.tenant],
                                      r.fut.n_ops)
                    self.acked_writes += 1
                self._audit_writes(1, ins, results, t1, True)
            except BaseException as e:  # noqa: BLE001 — a popped
                # request's future must resolve even on non-Sherman
                # failures (XLA runtime errors, OOM): _fail_batch
                # wraps, records, and re-raises KeyboardInterrupt
                self._fail_batch(ins, e)
        if dels:
            keys = np.concatenate([r.keys for r in dels]) \
                if len(dels) > 1 else dels[0].keys
            try:
                # a heap-backed tree frees slabs with the delete (the
                # reclaim path), else the plain engine delete
                found = self.value_heap.remove(keys) \
                    if self.value_heap is not None \
                    else self.eng.delete(keys)
                t1 = time.perf_counter()
                results = [np.asarray(found[off:off + r.fut.n_ops])
                           for off, r in zip(
                               np.cumsum([0] + [r.fut.n_ops
                                                for r in dels])[:-1],
                               dels)]
                self._ack_batch(dels, results, J.J_DELETE)
                self._quorum_gate()
                for r, fnd in zip(dels, results):
                    r.fut._set(fnd)
                    self.tracker.observe("delete", r.fut.n_ops,
                                         t1 - r.fut.t_submit)
                    self._note_served(self._tenants[r.fut.tenant],
                                      r.fut.n_ops)
                    self.acked_writes += 1
                self._audit_writes(2, dels, results, t1, False)
            except BaseException as e:  # noqa: BLE001
                self._fail_batch(dels, e)
        return True

    def _maybe_flush_scans(self) -> bool:
        reqs = self._take(("scan",), self.cfg.widths[-1])
        for r in reqs:
            try:
                res = self.value_heap.scan(r.ranges) \
                    if (r.resolve_payloads
                        and self.value_heap is not None) \
                    else self.eng.range_query_many(r.ranges)
                r.fut._set(res)
                self.tracker.observe(
                    "scan", r.fut.n_ops,
                    time.perf_counter() - r.fut.t_submit)
                self._note_served(self._tenants[r.fut.tenant],
                                  r.fut.n_ops)
            except BaseException as e:  # noqa: BLE001
                self._fail_batch([r], e)
        return bool(reqs)

    # -- telemetry -----------------------------------------------------------

    def _collect(self) -> dict:
        """The ``serve.`` pull collector (flat numbers, the ``slo.``
        shape): per-class end-to-end window stats + admission state."""
        flat = dict(self.tracker.collect())
        flat.update({
            "width": float(self._cur_width),
            "queued_ops": float(self._queued_ops),
            "admitted_ops": float(self.admitted_ops),
            "served_ops": float(self.served_ops),
            "acked_writes": float(self.acked_writes),
            "rejected_overload": float(self.rejected_overload),
            "rejected_degraded": float(self.rejected_degraded),
            "brownout": 1.0 if self._brownout else 0.0,
            "retraces": float(self.retraces),
            "prep_impl_device": 1.0 if any(
                getattr(s, "prep_impl", "host") == "device"
                for s in self._steps.values()) else 0.0,
            "write_combine": 1.0 if getattr(
                self.eng, "_write_combine", False) else 0.0,
            "dedup_hits": float(self.dedup_hits),
            "deadline_shed": float(self.deadline_shed),
            "duplicate_applies": float(self.duplicate_applies),
            "ack_quorum": float(self.cfg.ack_quorum),
            "quorum_acks": float(self.quorum_acks),
            "quorum_timeouts": float(self.quorum_timeouts),
            "quorum_wait_ms": round(float(self.quorum_wait_ms), 3),
        })
        return flat

    def stats(self) -> dict:
        """Receipt-grade nested stats (serve_bench's ``serve`` block):
        controller state, per-tenant shares, rejects, journal
        coalescing, cache sketch."""
        with self._lock:
            tenants = {
                name: {
                    "admitted_ops": st.admitted_ops,
                    "served_ops": st.served_ops,
                    "queued_ops": st.queued_ops,
                    "rejected_overload": st.rejected_overload,
                    "rejected_degraded": st.rejected_degraded,
                    "weight": st.weight,
                    "dedup_hits": st.dedup_hits,
                    "deadline_shed": st.deadline_shed,
                }
                for name, st in self._tenants.items()
            }
            contract = {
                "dedup_window": self.cfg.dedup_window,
                "dedup_hits": self.dedup_hits,
                "deadline_shed": self.deadline_shed,
                "duplicate_applies": self.duplicate_applies,
                "cached_rids": sum(len(st.dedup)
                                   for st in self._tenants.values()),
                "pending_rids": sum(len(st.pending)
                                    for st in self._tenants.values()),
            }
        total_served = max(1, self.served_ops)
        for t in tenants.values():
            t["share"] = round(t["served_ops"] / total_served, 4)
        out = {
            "fusion": self.cfg.fusion,
            "widths": list(self.cfg.widths),
            "p99_targets_ms": dict(self.cfg.p99_targets_ms),
            "max_queue_ops": self.cfg.max_queue_ops,
            "controller": self.controller.snapshot(),
            "calibration": {str(w): {k: round(v, 3)
                                     for k, v in c.items()}
                            for w, c in self.calibration.items()},
            "window": self.tracker.window(),
            "tenants": tenants,
            "admitted_ops": self.admitted_ops,
            "served_ops": self.served_ops,
            "acked_writes": self.acked_writes,
            "rejects": {"overload": self.rejected_overload,
                        "degraded": self.rejected_degraded},
            "dispatch_errors": self.dispatch_errors,
            "sealed": self._sealed,
            "retraces": self.retraces,
            "contract": contract,
            "quorum": {
                "ack_quorum": int(self.cfg.ack_quorum),
                "acks": self.quorum_acks,
                "timeouts": self.quorum_timeouts,
                "wait_ms": round(self.quorum_wait_ms, 3),
            },
            "request_plane": {
                "prep_impl": {str(w): getattr(s, "prep_impl", "host")
                              for w, s in self._steps.items()},
                "write_combine": bool(getattr(self.eng, "_write_combine",
                                              False)),
            },
        }
        if self.auditor is not None:
            out["audit"] = self.auditor.stats()
        if self.journal is not None:
            js = self.journal.stats()
            js["acks_per_fsync"] = (self.acked_writes / js["fsyncs"]
                                    if js["fsyncs"] else None)
            out["journal"] = js
        out["write_lane"] = self.cfg.write_lane
        if self.host_id is not None:
            # host attribution only under a multihost plane — hosts=1
            # receipts stay byte-identical to pre-plane builds
            out["host_id"] = int(self.host_id)
        if self.leaf_cache is not None:
            out["cache"] = {**self.leaf_cache.stats(),
                            "sketch": self.leaf_cache.sketch_stats()}
        if self.value_heap is not None:
            out["value_heap"] = self.value_heap.stats()
        return out


# ---------------------------------------------------------------------------
# Client-side retry policy + hedging
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RetryPolicy:
    """Client retry discipline against the front door's TYPED
    backpressure (:class:`ServeOverloadError` — the only retryable
    class by default; degraded/deadline rejects are policy decisions,
    not transient congestion).

    - capped exponential backoff with full jitter:
      ``sleep ~ U(0, min(cap, base * 2^attempt))`` — the classic
      thundering-herd antidote;
    - **writes retry ONLY with a request id**: a blind write retry can
      double-apply (the lost-update bug the dedup window kills), so a
      rid-less write gets exactly one attempt;
    - **read hedging**: after the tracker's observed p99 (times
      ``hedge_mult``) with no answer, a duplicate read is submitted
      and the first ack wins — tail-latency insurance that is safe
      precisely because reads are idempotent.  Never applied to
      writes.
    """

    max_attempts: int = 5
    base_backoff_ms: float = 2.0
    backoff_cap_ms: float = 200.0
    hedge_reads: bool = True
    hedge_mult: float = 3.0
    #: hedge trigger floor when the tracker has no p99 yet
    hedge_floor_ms: float = 25.0

    def backoff_s(self, attempt: int, rng: random.Random) -> float:
        cap = min(self.backoff_cap_ms,
                  self.base_backoff_ms * (2.0 ** attempt))
        return rng.uniform(0.0, cap) / 1e3


class RetryingClient:
    """One tenant's well-behaved client over a :class:`ShermanServer`:
    assigns request ids to writes, applies :class:`RetryPolicy`, and
    carries its own deadline default.  The contract drill's client
    threads (and any embedding application) use this instead of raw
    ``submit`` so retries are exactly-once by construction."""

    def __init__(self, srv: ShermanServer, tenant: str = "default",
                 policy: RetryPolicy | None = None, seed: int = 0,
                 deadline_ms: float | None = None):
        self.srv = srv
        self.tenant = tenant
        self.policy = policy or RetryPolicy()
        self.deadline_ms = deadline_ms
        self._rng = random.Random(seed)
        # client-assigned request ids: unique per (client seed, op) —
        # the exactly-once join key across retries AND across crashes
        self._rid = (seed & 0xFFFF) << 48
        self.retries = 0
        self.hedges = 0
        self.rejects = 0

    def next_rid(self) -> int:
        self._rid += 1
        return self._rid

    # -- reads ----------------------------------------------------------------

    def _hedge_after_s(self) -> float:
        w = self.srv.tracker.window().get("read") or {}
        p99 = w.get("p99_ms") or 0.0
        return max(self.policy.hedge_floor_ms,
                   self.policy.hedge_mult * p99) / 1e3

    def read(self, keys, deadline_ms=None):
        """Submit-with-retry + hedging; returns ``(values, found)``.
        Raises the last typed error when every attempt was rejected."""
        pol = self.policy
        deadline_ms = deadline_ms if deadline_ms is not None \
            else self.deadline_ms
        last: BaseException | None = None
        for attempt in range(pol.max_attempts):
            try:
                fut = self.srv.submit("read", keys, tenant=self.tenant,
                                      deadline_ms=deadline_ms)
            except (ServeOverloadError, DegradedError) as e:
                self.rejects += 1
                last = e
                self.retries += 1
                time.sleep(pol.backoff_s(attempt, self._rng))
                continue
            if not pol.hedge_reads:
                return fut.result(timeout=60)
            try:
                return fut.result(timeout=self._hedge_after_s())
            except StateError:
                pass  # primary still in flight past p99: hedge it
            except DeadlineExceededError as e:
                last = e
                self.retries += 1
                continue  # shed while queued: re-submit is safe
            hedge = None
            try:
                hedge = self.srv.submit("read", keys,
                                        tenant=self.tenant,
                                        deadline_ms=deadline_ms)
                self.hedges += 1
            except (ServeOverloadError, DegradedError):
                pass  # overloaded: the primary remains the only horse
            # first ack wins (both are the same idempotent read)
            while True:
                for f in (fut, hedge):
                    if f is not None and f.done():
                        try:
                            return f.result()
                        except DeadlineExceededError as e:
                            # shed copy: fall through to the other
                            if f is fut:
                                fut = None
                            else:
                                hedge = None
                            last = e
                            break
                if fut is None and hedge is None:
                    break
                time.sleep(0.0005)
            self.retries += 1
        raise last if last is not None else StateError(
            "read retries exhausted")

    # -- writes (exactly-once: rid-gated retry) -------------------------------

    def _write(self, op: str, keys, values=None, rid=None,
               deadline_ms=None):
        pol = self.policy
        deadline_ms = deadline_ms if deadline_ms is not None \
            else self.deadline_ms
        if rid is None:
            # no request id = no retry budget: a blind write retry can
            # double-apply, which the client refuses to risk
            fut = self.srv.submit(op, keys, values, tenant=self.tenant,
                                  deadline_ms=deadline_ms)
            return fut.result(timeout=60)
        last: BaseException | None = None
        for attempt in range(pol.max_attempts):
            try:
                fut = self.srv.submit(op, keys, values,
                                      tenant=self.tenant, rid=rid,
                                      deadline_ms=deadline_ms)
                return fut.result(timeout=60)
            except (ServeOverloadError, DeadlineExceededError) as e:
                # both mean "never applied": the rid makes the
                # re-submit exactly-once even if that ever changed
                last = e
                self.retries += 1
                time.sleep(pol.backoff_s(attempt, self._rng))
        raise last if last is not None else StateError(
            f"{op} retries exhausted")

    def insert(self, keys, values, rid=None, deadline_ms=None):
        """Exactly-once insert: ``rid`` defaults to a fresh
        client-assigned id (pass an explicit one to RETRY a prior
        attempt across a timeout or a crash)."""
        return self._write("insert", keys, values,
                           rid=self.next_rid() if rid is None else rid,
                           deadline_ms=deadline_ms)

    def delete(self, keys, rid=None, deadline_ms=None):
        return self._write("delete", keys,
                           rid=self.next_rid() if rid is None else rid,
                           deadline_ms=deadline_ms)

    def stats(self) -> dict:
        return {"retries": self.retries, "hedges": self.hedges,
                "rejects": self.rejects}
