"""Process-wide metrics registry: counters, gauges, histograms.

Design constraints (from the benchmark drivers that motivated it):

- **Hot-path increments are counter-increment cheap.**  ``Counter.inc``
  is one attribute add — no locks, no dict lookups, no branches.  The
  single-threaded driver path (bench loops, the batched engine) pays
  ~40 ns per increment; under free threading a data race can at worst
  undercount (increments are not atomic read-modify-writes across
  threads), which is the standard statsd/prometheus-client trade for a
  lock-free hot path.  Metric *creation* takes the registry lock.
- **Snapshot/delta semantics.**  ``snapshot()`` flattens every metric
  to plain Python values; ``delta(before, after)`` diffs two snapshots
  so a test or bench can assert "this region cost N DSM reads" without
  resetting global state.
- **Pull collectors.**  State that lives off-host (the DSM's device
  counter array) registers a callable; snapshots invoke it and merge
  the returned dict under the collector's prefix.  Collectors are held
  by weakref-bound closures at the call sites, and a collector that
  raises is skipped (recorded under ``_collector_errors``) — a donated
  device buffer mid-step must not take the whole snapshot down.
"""

from __future__ import annotations

import math
import threading
from typing import Callable

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "counter", "gauge", "histogram", "snapshot", "delta",
    "register_collector", "unregister_collector", "get_registry",
]


class Counter:
    """Monotonic event counter.  ``inc`` is the hot path: no locks."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def snapshot(self) -> int:
        return self.value


class Gauge:
    """Last-value-wins instantaneous measurement."""

    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def add(self, v: float) -> None:
        self.value += float(v)

    def snapshot(self) -> float:
        return self.value


class Histogram:
    """Log2-bucketed value distribution (the native ``LatencyHistogram``
    shape, host-side and unit-agnostic): 64 power-of-two buckets cover
    any non-negative range; count/sum/min/max are exact, percentiles
    bucket-resolved (within 2x — the same fidelity class the reference's
    fixed-width histogram trades at its range cap)."""

    __slots__ = ("name", "buckets", "count", "sum", "min", "max")

    def __init__(self, name: str):
        self.name = name
        self.buckets = [0] * 64
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def record(self, v: float, n: int = 1) -> None:
        b = max(0, float(v)).__trunc__().bit_length()  # 0 -> bucket 0
        self.buckets[min(b, 63)] += n
        self.count += n
        self.sum += float(v) * n
        if v < self.min:
            self.min = float(v)
        if v > self.max:
            self.max = float(v)

    def percentile(self, q: float) -> float:
        """Bucket-upper-bound estimate of the q-th percentile (q in
        [0, 100]); 0.0 on an empty histogram."""
        if self.count == 0:
            return 0.0
        target = q / 100.0 * self.count
        seen = 0
        for b, c in enumerate(self.buckets):
            seen += c
            if seen >= target and c:
                return float((1 << b) - 1) if b else 0.0
        return float(self.max)

    def snapshot(self) -> dict:
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
            "mean": (self.sum / self.count) if self.count else None,
            "p50": self.percentile(50),
            "p99": self.percentile(99),
        }


class MetricsRegistry:
    """Named metrics + pull collectors; get-or-create is idempotent."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, Counter | Gauge | Histogram] = {}
        self._collectors: dict[str, Callable[[], dict]] = {}

    def _get(self, name: str, cls):
        m = self._metrics.get(name)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = cls(name)
                    self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError(f"metric {name!r} already registered as "
                            f"{type(m).__name__}, not {cls.__name__}")
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def metrics(self) -> list:
        """The live metric objects (typed view — the Prometheus
        exposition needs counter/gauge/histogram kinds, which the flat
        snapshot erases)."""
        with self._lock:
            return list(self._metrics.values())

    def register_collector(self, prefix: str,
                           fn: Callable[[], dict]) -> None:
        """Merge ``fn()`` (a flat name -> number dict) into every
        snapshot under ``prefix.``.  Re-registering a prefix replaces
        the previous collector (a rebuilt DSM supersedes its ancestor)."""
        with self._lock:
            self._collectors[prefix] = fn

    def unregister_collector(self, prefix: str) -> None:
        with self._lock:
            self._collectors.pop(prefix, None)

    def snapshot(self) -> dict:
        """Flatten everything to plain values: counters -> int, gauges
        -> float, histograms -> dict, collectors -> prefixed entries."""
        out: dict = {}
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors.items())
        for m in metrics:
            out[m.name] = m.snapshot()
        errs = []
        for prefix, fn in collectors:
            try:
                for k, v in fn().items():
                    out[f"{prefix}.{k}"] = v
            except Exception as e:  # donated buffer mid-step, dead ref…
                errs.append(f"{prefix}: {type(e).__name__}: {e}")
        if errs:
            out["_collector_errors"] = errs
        return out

    def reset(self) -> None:
        """Zero every metric IN PLACE (test isolation).

        Registrations and collectors survive: instrumentation sites
        (btree, dsm, transport) bind their Counter objects at import,
        so dropping the objects would disconnect them from snapshots
        for the life of the process — zeroing keeps the bindings live.
        """
        with self._lock:
            for m in self._metrics.values():
                if isinstance(m, Counter):
                    m.value = 0
                elif isinstance(m, Gauge):
                    m.value = 0.0
                else:
                    m.buckets = [0] * 64
                    m.count = 0
                    m.sum = 0.0
                    m.min = math.inf
                    m.max = -math.inf


def delta(before: dict, after: dict) -> dict:
    """Diff two snapshots: numeric entries subtract (counter deltas),
    histogram dicts diff their ``count``/``sum``, and keys only present
    in ``after`` (metrics born inside the region) count from zero."""
    out: dict = {}
    for k, v in after.items():
        if k.startswith("_"):
            continue
        b = before.get(k)
        if isinstance(v, dict):
            bc = b if isinstance(b, dict) else {}
            out[k] = {"count": v.get("count", 0) - bc.get("count", 0),
                      "sum": (v.get("sum") or 0) - (bc.get("sum") or 0)}
        elif isinstance(v, (int, float)):
            out[k] = v - (b if isinstance(b, (int, float)) else 0)
    return out


# -- process-wide default registry -------------------------------------------

_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _REGISTRY


def counter(name: str) -> Counter:
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return _REGISTRY.gauge(name)


def histogram(name: str) -> Histogram:
    return _REGISTRY.histogram(name)


def snapshot() -> dict:
    return _REGISTRY.snapshot()


def register_collector(prefix: str, fn: Callable[[], dict]) -> None:
    _REGISTRY.register_collector(prefix, fn)


def unregister_collector(prefix: str) -> None:
    _REGISTRY.unregister_collector(prefix)
