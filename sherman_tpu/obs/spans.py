"""Span tracing: nested wall-clock spans with Chrome-trace export.

Two tiers, sharing one recording substrate:

- :class:`StepTrace` — the legacy flat micro-tracer (phase -> spans)
  for driver loops, kept API-identical to ``utils.trace.StepTrace``
  (which now re-exports from here).  ~100 ns per record.
- :class:`SpanTracer` — nested spans with thread-safe recording: each
  thread keeps its own span stack (``threading.local``), completed
  spans append to one shared list under a lock (completion is off the
  per-op hot path — it happens once per *phase*, not per request).
  Export is Chrome-trace-event JSON (``"X"`` complete events with
  microsecond timestamps), loadable in ``chrome://tracing`` and
  Perfetto (https://ui.perfetto.dev — open the file directly).

:func:`device_trace` (the XLA/TPU profiler capture) also lives here;
it complements host spans with on-chip kernel/DMA timelines.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import defaultdict

__all__ = ["StepTrace", "SpanTracer", "device_trace", "get_tracer", "span"]


class StepTrace:
    """Accumulate (phase -> spans) across a driver loop.

    >>> tr = StepTrace()
    >>> with tr.span("descend"):
    ...     ...
    >>> tr.summary()  # {'descend': {'n': 1, 'total_s': ..., 'mean_ms': ...}}
    """

    def __init__(self):
        self._spans = defaultdict(list)

    @contextlib.contextmanager
    def span(self, name: str):
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            self._spans[name].append(time.perf_counter() - t0)

    def record(self, name: str, seconds: float) -> None:
        self._spans[name].append(float(seconds))

    def summary(self) -> dict[str, dict[str, float]]:
        out = {}
        for name, spans in self._spans.items():
            tot = sum(spans)
            out[name] = {"n": len(spans), "total_s": tot,
                         "mean_ms": tot / len(spans) * 1e3}
        return out

    def report(self) -> str:
        lines = []
        for name, s in sorted(self.summary().items(),
                              key=lambda kv: -kv[1]["total_s"]):
            lines.append(f"{name:24s} n={s['n']:<6d} "
                         f"total={s['total_s']:8.3f}s "
                         f"mean={s['mean_ms']:8.3f}ms")
        return "\n".join(lines)


class SpanTracer:
    """Nested spans, thread-safe, Chrome-trace exportable.

    Each completed span records (name, start_us, dur_us, tid, depth);
    nesting comes from a per-thread stack so concurrent host clients
    (the local-lock tier's use case) never corrupt each other's spans.
    Bounded: beyond ``max_events`` completed spans the tracer keeps
    aggregating summaries but stops appending events (a multi-hour
    churn run must not grow an unbounded list); ``dropped`` counts the
    overflow so exports can say so.
    """

    def __init__(self, max_events: int = 1_000_000):
        self._lock = threading.Lock()
        self._events: list[tuple] = []
        self._agg = defaultdict(lambda: [0, 0.0])  # name -> [n, total_s]
        self._tls = threading.local()
        self._t0 = time.perf_counter()
        self.max_events = max_events
        self.dropped = 0
        # optional span-close subscriber (name, dur_s, depth) — the
        # flight recorder's feed; called OUTSIDE the lock, per span
        # completion (per phase, not per op)
        self.on_close = None

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    @contextlib.contextmanager
    def span(self, name: str, **args):
        st = self._stack()
        st.append(name)
        t0 = time.perf_counter()
        try:
            yield self
        finally:
            t1 = time.perf_counter()
            st.pop()
            self._record(name, t0, t1, len(st), args or None)

    def record(self, name: str, seconds: float) -> None:
        """StepTrace-compatible after-the-fact record (the span ends
        now and lasted ``seconds``)."""
        t1 = time.perf_counter()
        self._record(name, t1 - float(seconds), t1, len(self._stack()),
                     None)

    def _record(self, name, t0, t1, depth, args) -> None:
        tid = threading.get_ident()
        # an after-the-fact record() may claim a start BEFORE the
        # tracer's epoch; clip the exported event to the trace window
        # (negative ts breaks the Chrome trace-event contract) while
        # the aggregate keeps the true duration
        e0 = max(t0, self._t0)
        with self._lock:
            a = self._agg[name]
            a[0] += 1
            a[1] += t1 - t0
            if len(self._events) < self.max_events:
                self._events.append((name, e0 - self._t0, t1 - e0, tid,
                                     depth, args))
            else:
                self.dropped += 1
        cb = self.on_close
        if cb is not None:
            cb(name, t1 - t0, depth)

    # -- views ---------------------------------------------------------------

    def summary(self) -> dict[str, dict[str, float]]:
        """StepTrace-shaped aggregate: full history even past the event
        cap."""
        with self._lock:
            return {name: {"n": n, "total_s": tot,
                           "mean_ms": tot / n * 1e3}
                    for name, (n, tot) in self._agg.items()}

    def report(self) -> str:
        lines = []
        for name, s in sorted(self.summary().items(),
                              key=lambda kv: -kv[1]["total_s"]):
            lines.append(f"{name:24s} n={s['n']:<6d} "
                         f"total={s['total_s']:8.3f}s "
                         f"mean={s['mean_ms']:8.3f}ms")
        return "\n".join(lines)

    def chrome_trace(self) -> dict:
        """Chrome-trace-event JSON object: ``{"traceEvents": [...]}``.

        Complete ("X") events with microsecond timestamps; one pid
        (this process), tids preserved so multi-threaded drivers render
        as parallel tracks.  Load in chrome://tracing or Perfetto.
        """
        pid = os.getpid()
        with self._lock:
            events = list(self._events)
        trace_events = [
            {"name": name, "ph": "X", "pid": pid, "tid": tid,
             "ts": round(start * 1e6, 3), "dur": round(dur * 1e6, 3),
             "cat": "sherman_tpu",
             **({"args": args} if args else {})}
            for name, start, dur, tid, _depth, args in events
        ]
        meta = {"dropped_events": self.dropped} if self.dropped else {}
        return {"traceEvents": trace_events, "displayTimeUnit": "ms",
                "otherData": {"tracer": "sherman_tpu.obs", **meta}}

    def export_chrome(self, path: str) -> str:
        """Write :meth:`chrome_trace` to ``path``; returns the path."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)
        return path

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._agg.clear()
            self.dropped = 0
            self._t0 = time.perf_counter()


@contextlib.contextmanager
def device_trace(log_dir: str):
    """Capture an XLA device trace for the enclosed block.

    View with TensorBoard's profile plugin or Perfetto.  No-op overhead
    outside the block; inside, the runtime records kernel/DMA timelines.
    """
    import jax
    with jax.profiler.trace(log_dir):
        yield


# -- process-wide default tracer ---------------------------------------------

_TRACER = SpanTracer()


def get_tracer() -> SpanTracer:
    return _TRACER


def span(name: str, **args):
    """Span on the default tracer — the one instrumentation sites use."""
    return _TRACER.span(name, **args)
