"""Per-op-class SLO telemetry: windowed rates + streaming latency.

The paper's evaluation is tail-latency-first (Sherman's headline is p99
under write-heavy skew), and the serving front door the ROADMAP names
cannot pick step widths against a per-class p99 target until something
*measures* per-class latency continuously.  The registry's
:class:`~sherman_tpu.obs.registry.Histogram` is a coarse (2x-fidelity)
log2 profile tool; this module is the SLO-grade layer on top:

- :class:`LatencyTracker` — a streaming log-bucketed histogram with 8
  linear sub-buckets per octave (the HdrHistogram shape), so quantile
  estimates carry <= 12.5% bucket error (rank-interpolated within the
  bucket, typically a few %) at 512 ints of state.  ``record`` is a
  handful of integer ops — no locks, no allocation; under free
  threading a race can at worst undercount (the registry's documented
  trade).
- :class:`WindowedRate` — sliding-window ops/s over a granule ring
  (no per-op timestamps, no unbounded lists).
- :class:`SloTracker` — the per-op-class front: every *batch wall* is
  attributed to its op class (``read`` / ``insert`` / ``delete`` /
  ``mixed`` / ``scan``) as amortized per-op latency — in the batched
  execution model a client op's completion latency IS its batch's wall
  (bench.py's step-span latency model), so a batch of ``ops`` requests
  served in ``wall_s`` records one wall sample *weighted by ops* and
  adds ``ops`` to the class's windowed rate.  :meth:`SloTracker.window`
  publishes, per class and per sliding window: ``ops_s``, ``p50_ms``,
  ``p99_ms``, ``p999_ms`` — exactly the width x latency frontier data
  an adaptive batcher consumes.

Window semantics: percentiles are two-generation — a current and a
previous window-sized tracker, rotated every ``window_s``; the
published quantiles merge both, so the view always covers at least one
full window and at most two (the standard rolling-histogram trade; no
per-sample timestamps).

Process-wide default: :func:`observe` / :func:`observe_op` feed the
default tracker; :func:`get_slo` registers it as a pull collector so
every registry snapshot (and therefore the Prometheus exposition and
the bench JSON ``obs`` section) carries flat ``slo.<class>.<stat>``
keys.  ``SHERMAN_SLO=0`` turns the default-tracker observers into
no-ops (the obs-on/off A/B knob; the acceptance test pins the staged
step's obs cost < 2% of its wall).

Instrumented sites: the BatchedEngine host entry points (search ->
``read``, insert -> ``insert``, delete -> ``delete``, mixed ->
``mixed``, range_query_many -> ``scan``) and the device-staged step
factories (``make_staged_step(...).record_slo`` — the bench's
sustained windows attribute whole windows at once, nothing per step).
"""

from __future__ import annotations

import math
import os
import threading
import time

__all__ = [
    "OP_CLASSES", "LatencyTracker", "WindowedRate", "SloTracker",
    "get_slo", "observe", "observe_op", "slo_window", "enabled",
]

# the serving op classes every batch wall is attributed to
OP_CLASSES = ("read", "insert", "delete", "mixed", "scan")

_SUB = 8          # linear sub-buckets per octave (3 mantissa bits)
_NBUCKETS = 512   # covers the full 63-bit ns range (u64 latencies)


class LatencyTracker:
    """Streaming log-bucketed latency histogram (ns resolution).

    Bucket layout: values below 8 ns are exact (buckets 0-7); above,
    octave ``o = bit_length - 1`` splits into 8 linear sub-buckets, so
    bucket width is value/8 — quantiles resolve within 12.5% before the
    in-bucket rank interpolation tightens them further.  ``record`` is
    integer ops + two adds; safe (undercount-at-worst) under threads.
    """

    __slots__ = ("buckets", "count", "sum_ns", "min_ns", "max_ns")

    def __init__(self):
        self.buckets = [0] * _NBUCKETS
        self.count = 0
        self.sum_ns = 0
        self.min_ns = None
        self.max_ns = None

    @staticmethod
    def _bucket(v: int) -> int:
        if v < 8:
            return v if v > 0 else 0
        o = v.bit_length() - 1
        return (o - 3) * _SUB + (v >> (o - 3))

    @staticmethod
    def _bucket_bounds(idx: int) -> tuple[int, int]:
        """[lo, hi) value range of bucket ``idx``."""
        if idx < 8:
            return idx, idx + 1
        j = idx - 8
        o = j // _SUB + 3
        m = j % _SUB + 8
        lo = m << (o - 3)
        return lo, lo + (1 << (o - 3))

    def record(self, seconds: float, n: int = 1) -> None:
        """One latency sample of ``seconds``, weighted ``n`` (a batch
        wall attributed to each of its n ops records once with n)."""
        v = int(seconds * 1e9)
        if v < 0:
            v = 0
        self.buckets[self._bucket(v)] += n
        self.count += n
        self.sum_ns += v * n
        if self.min_ns is None or v < self.min_ns:
            self.min_ns = v
        if self.max_ns is None or v > self.max_ns:
            self.max_ns = v

    def merge(self, other: "LatencyTracker") -> "LatencyTracker":
        """Bucket-wise accumulate ``other`` into self (window merging)."""
        ob = other.buckets
        sb = self.buckets
        for i in range(_NBUCKETS):
            if ob[i]:
                sb[i] += ob[i]
        self.count += other.count
        self.sum_ns += other.sum_ns
        if other.min_ns is not None and (
                self.min_ns is None or other.min_ns < self.min_ns):
            self.min_ns = other.min_ns
        if other.max_ns is not None and (
                self.max_ns is None or other.max_ns > self.max_ns):
            self.max_ns = other.max_ns
        return self

    def percentile_ns(self, q: float) -> float:
        """Rank-interpolated q-th percentile (q in [0, 100]); 0.0 when
        empty.  Clamped into [min, max] so the bucket upper bound can
        never report a tail beyond the largest recorded value."""
        if self.count == 0:
            return 0.0
        target = q / 100.0 * self.count
        seen = 0
        for i, c in enumerate(self.buckets):
            if not c:
                continue
            if seen + c >= target:
                lo, hi = self._bucket_bounds(i)
                frac = (target - seen) / c
                est = lo + (hi - lo) * frac
                if self.min_ns is not None:
                    est = max(est, self.min_ns)
                if self.max_ns is not None:
                    est = min(est, self.max_ns)
                return est
            seen += c
        return float(self.max_ns or 0)

    def percentile_ms(self, q: float) -> float:
        return self.percentile_ns(q) / 1e6

    def snapshot(self) -> dict:
        c = self.count
        return {
            "count": c,
            "sum_ms": self.sum_ns / 1e6,
            "mean_ms": (self.sum_ns / c / 1e6) if c else None,
            "min_ms": (self.min_ns / 1e6) if c else None,
            "max_ms": (self.max_ns / 1e6) if c else None,
            "p50_ms": self.percentile_ms(50),
            "p99_ms": self.percentile_ms(99),
            "p999_ms": self.percentile_ms(99.9),
        }


class WindowedRate:
    """Sliding-window event rate over a granule ring.

    ``granules`` fixed-width time cells cover ``window_s``; ``add``
    lands counts in the current cell (lazily zeroing cells the clock
    skipped), ``rate`` sums live cells over the covered span.  O(1)
    memory, no timestamps per event; resolution is one granule.
    """

    def __init__(self, window_s: float = 10.0, granules: int = 20):
        assert window_s > 0 and granules > 0
        self.window_s = float(window_s)
        self.granules = int(granules)
        self._gw = self.window_s / self.granules
        self._counts = [0.0] * self.granules
        self._gids = [-1] * self.granules
        self._t0: float | None = None  # first add (startup partial window)

    def add(self, n: float, now: float) -> None:
        if self._t0 is None:
            self._t0 = now
        g = int(now / self._gw)
        i = g % self.granules
        if self._gids[i] != g:
            self._gids[i] = g
            self._counts[i] = 0.0
        self._counts[i] += n

    def total(self, now: float) -> float:
        """Events inside the window ending at ``now``."""
        g = int(now / self._gw)
        lo = g - self.granules + 1
        return sum(c for c, gid in zip(self._counts, self._gids)
                   if lo <= gid <= g)

    def rate(self, now: float) -> float:
        """Events/s over the window (partial-window aware at startup,
        so a 2-second-old tracker divides by 2 s, not the full window —
        even when 2 s is less than one granule; a long-window tracker
        queried right after a short burst must not dilute the rate by
        the granule width).  Only the degenerate zero-elapsed query
        falls back to a granule of cover."""
        if self._t0 is None:
            return 0.0
        covered = min(self.window_s, now - self._t0)
        if covered <= 0.0:
            covered = self._gw
        return self.total(now) / covered


class _ClassStats:
    """One op class's rolling state: two-generation latency trackers
    (merged view >= one full window), a windowed rate, and cumulative
    totals."""

    __slots__ = ("cur", "prev", "cur_start", "rate",
                 "ops_total", "batches_total", "wall_s_total")

    def __init__(self, window_s: float, now: float):
        self.cur = LatencyTracker()
        self.prev = LatencyTracker()
        self.cur_start = now
        self.rate = WindowedRate(window_s)
        self.ops_total = 0
        self.batches_total = 0
        self.wall_s_total = 0.0

    def rotate_if_due(self, window_s: float, now: float,
                      lock: threading.Lock) -> None:
        # Fast path is one float compare; the swap itself runs under the
        # tracker lock with a due re-check — an observe() racing a
        # scrape-thread window() at the boundary must rotate ONCE, not
        # twice (a double swap would shunt the just-filled tracker
        # straight through prev and publish a near-empty window).
        if now - self.cur_start >= window_s:
            with lock:
                if now - self.cur_start >= window_s:
                    self.prev = self.cur
                    self.cur = LatencyTracker()
                    self.cur_start = now

    def merged(self) -> LatencyTracker:
        m = LatencyTracker()
        m.merge(self.prev)
        m.merge(self.cur)
        return m


class SloTracker:
    """Per-op-class SLO accounting (see module docstring).

    ``observe(cls, ops, wall_s, batches=k)`` attributes a window of
    ``k`` batches totalling ``ops`` ops that took ``wall_s`` seconds:
    the per-batch wall (``wall_s / k``) is recorded as each op's
    completion latency (weight ``ops``), and ``ops`` land in the
    class's sliding rate.  ``observe_op`` records a single op's own
    latency (the open-loop latency bench's sample shape).
    """

    def __init__(self, window_s: float = 10.0, clock=time.monotonic):
        self.window_s = float(window_s)
        self._clock = clock
        self._lock = threading.Lock()   # class creation + rotation only
        self._classes: dict[str, _ClassStats] = {}

    def _stats(self, op_class: str, now: float) -> _ClassStats:
        st = self._classes.get(op_class)
        if st is None:
            with self._lock:
                st = self._classes.get(op_class)
                if st is None:
                    st = _ClassStats(self.window_s, now)
                    self._classes[op_class] = st
        return st

    def observe(self, op_class: str, ops: int, wall_s: float, *,
                batches: int = 1, now: float | None = None) -> None:
        if ops <= 0:
            return
        now = self._clock() if now is None else now
        st = self._stats(op_class, now)
        st.rotate_if_due(self.window_s, now, self._lock)
        st.cur.record(wall_s / max(1, batches), int(ops))
        st.rate.add(ops, now)
        st.ops_total += int(ops)
        st.batches_total += int(batches)
        st.wall_s_total += float(wall_s)

    def observe_op(self, op_class: str, latency_s: float, *,
                   now: float | None = None) -> None:
        self.observe(op_class, 1, latency_s, batches=1, now=now)

    def window(self, now: float | None = None) -> dict:
        """{class: {ops_s, p50_ms, p99_ms, p999_ms, window_ops,
        ops_total, batches_total}} for every observed class."""
        now = self._clock() if now is None else now
        out = {}
        for cls, st in list(self._classes.items()):
            st.rotate_if_due(self.window_s, now, self._lock)
            m = st.merged()
            out[cls] = {
                "ops_s": st.rate.rate(now),
                "p50_ms": m.percentile_ms(50),
                "p99_ms": m.percentile_ms(99),
                "p999_ms": m.percentile_ms(99.9),
                "window_ops": m.count,
                "ops_total": st.ops_total,
                "batches_total": st.batches_total,
            }
        return out

    def collect(self) -> dict:
        """Flat {"<class>.<stat>": number} view — the registry pull
        collector (every snapshot / Prometheus scrape carries it)."""
        flat = {}
        for cls, stats in self.window().items():
            for k, v in stats.items():
                flat[f"{cls}.{k}"] = round(float(v), 6)
        return flat

    def reset(self) -> None:
        with self._lock:
            self._classes.clear()


# -- process-wide default tracker ---------------------------------------------

_TRACKER = SloTracker(
    window_s=float(os.environ.get("SHERMAN_SLO_WINDOW_S", 10.0)))
_REGISTERED = [False]


def enabled() -> bool:
    """The default-tracker observers honor ``SHERMAN_SLO=0`` (the
    obs-on/off A/B knob); per-instance trackers are always live."""
    return os.environ.get("SHERMAN_SLO", "1") != "0"


def get_slo() -> SloTracker:
    """The default tracker, registered as the ``slo.`` pull collector
    on first access so snapshots and expositions carry it."""
    if not _REGISTERED[0]:
        from sherman_tpu.obs import registry as _registry
        _registry.register_collector("slo", _TRACKER.collect)
        _REGISTERED[0] = True
    return _TRACKER


def observe(op_class: str, ops: int, wall_s: float, *,
            batches: int = 1) -> None:
    if enabled():
        get_slo().observe(op_class, ops, wall_s, batches=batches)


def observe_op(op_class: str, latency_s: float) -> None:
    if enabled():
        get_slo().observe_op(op_class, latency_s)


def slo_window() -> dict:
    """The default tracker's per-class window — bench.py's ``slo``
    JSON section."""
    return get_slo().window()
