"""Exporters: JSON dumps, periodic snapshots, Prometheus exposition.

``bench.py`` and the tools/ drivers report through here instead of
hand-formatting their own strings:

- :func:`obs_section` — the dict a driver embeds in its JSON output
  (``{"counters": ..., "spans": ...}``), built from the default
  registry + tracer.
- :func:`dump` — write a full observability dump (metrics snapshot +
  span summary + Chrome trace events) to one JSON file.
- :func:`write_snapshot_jsonl` / :class:`PeriodicExporter` — append
  timestamped registry snapshots to a JSONL file, manually or on a
  background interval.  ``PeriodicExporter(..., fmt="prom")`` instead
  rewrites a Prometheus textfile each tick — the node-exporter
  textfile-collector deployment shape.
- :func:`prometheus_text` / :func:`write_prometheus` — Prometheus
  text-format exposition (0.0.4): counters as ``_total``, gauges as
  gauges, histograms as summaries with p50/p99/p999 quantiles, pull
  collectors (``dsm.*``, ``slo.*``) as untyped gauges.
  :func:`write_prometheus` is atomic (tmp + rename) per the textfile
  collector's contract.
- :class:`MetricsServer` / :func:`maybe_serve_http` — an optional
  stdlib HTTP scrape endpoint (``/metrics``), armed by
  ``SHERMAN_METRICS_PORT`` (0/unset = off); daemon thread, no
  dependencies — metrics leave the process without parsing bench JSON.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time

from sherman_tpu.obs import registry as _registry
from sherman_tpu.errors import ConfigError
from sherman_tpu.obs import spans as _spans

__all__ = ["dump", "obs_section", "write_snapshot_jsonl",
           "PeriodicExporter", "prometheus_text", "write_prometheus",
           "MetricsServer", "maybe_serve_http", "METRICS_PORT_ENV"]

METRICS_PORT_ENV = "SHERMAN_METRICS_PORT"


def obs_section(reg=None, tracer=None) -> dict:
    """The ``obs`` dict drivers embed in their JSON output."""
    reg = reg if reg is not None else _registry.get_registry()
    tracer = tracer if tracer is not None else _spans.get_tracer()
    return {"counters": reg.snapshot(), "spans": tracer.summary()}


def dump(path: str, reg=None, tracer=None, *, extra: dict | None = None
         ) -> str:
    """Write metrics + spans + Chrome trace events to ``path`` (JSON).

    The file doubles as a Perfetto-loadable trace: ``traceEvents`` is
    top-level per the Chrome trace-event spec, with the metrics
    snapshot riding in ``otherData``.  Returns the path."""
    reg = reg if reg is not None else _registry.get_registry()
    tracer = tracer if tracer is not None else _spans.get_tracer()
    doc = tracer.chrome_trace()
    doc["otherData"].update({
        "metrics": reg.snapshot(),
        "span_summary": tracer.summary(),
        "wall_time": time.time(),
        **(extra or {}),
    })
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def write_snapshot_jsonl(path: str, reg=None, *,
                         extra: dict | None = None) -> None:
    """Append one timestamped registry snapshot as a JSONL line."""
    reg = reg if reg is not None else _registry.get_registry()
    line = {"t": time.time(), "metrics": reg.snapshot(), **(extra or {})}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(line) + "\n")


class PeriodicExporter:
    """Background-thread periodic exporter: JSONL append (default) or
    Prometheus textfile rewrite (``fmt="prom"``).

    >>> ex = PeriodicExporter("obs.jsonl", interval_s=10.0)
    >>> ex.start()
    ...
    >>> ex.stop()   # writes one final snapshot

    Snapshots invoke registry collectors (which may touch device
    arrays); drivers whose collectors are not safe mid-step should
    snapshot manually at step boundaries instead.
    """

    def __init__(self, path: str, interval_s: float = 10.0, reg=None,
                 fmt: str = "jsonl"):
        assert fmt in ("jsonl", "prom"), fmt
        self.path = path
        self.interval_s = interval_s
        self.reg = reg if reg is not None else _registry.get_registry()
        self.fmt = fmt
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _write(self, extra=None) -> None:
        if self.fmt == "prom":
            write_prometheus(self.path, self.reg)
        else:
            write_snapshot_jsonl(self.path, self.reg, extra=extra)

    def start(self) -> "PeriodicExporter":
        assert self._thread is None, "already started"
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="obs-exporter")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._write()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None
        self._write(extra={"final": True})

    def __enter__(self) -> "PeriodicExporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# -- Prometheus exposition ----------------------------------------------------

def _prom_name(name: str, prefix: str = "sherman") -> str:
    return f"{prefix}_" + re.sub(r"[^a-zA-Z0-9_:]", "_", name)


def _prom_num(v) -> str:
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f) if not f.is_integer() else str(int(f))


def prometheus_text(reg=None, prefix: str = "sherman") -> str:
    """Render the registry as Prometheus text exposition (0.0.4).

    Counters end in ``_total``, gauges are gauges, histograms render as
    summaries (``quantile`` labels for p50/p99/p999 + ``_sum``/
    ``_count``), and pull-collector values (``dsm.*``, ``slo.*`` — flat
    numbers whose kind the collector erased) render as untyped gauges.
    Dots in metric names become underscores under the ``sherman_``
    namespace (``dsm.read_ops`` -> ``sherman_dsm_read_ops_total`` for
    typed counters, ``sherman_dsm_read_ops`` for collector values).
    """
    reg = reg if reg is not None else _registry.get_registry()
    lines: list[str] = []
    typed_names = set()
    for m in reg.metrics():
        typed_names.add(m.name)
        p = _prom_name(m.name, prefix)
        if isinstance(m, _registry.Counter):
            lines.append(f"# TYPE {p}_total counter")
            lines.append(f"{p}_total {_prom_num(m.value)}")
        elif isinstance(m, _registry.Gauge):
            lines.append(f"# TYPE {p} gauge")
            lines.append(f"{p} {_prom_num(m.value)}")
        else:  # Histogram -> summary
            lines.append(f"# TYPE {p} summary")
            for q, pct in (("0.5", 50), ("0.99", 99), ("0.999", 99.9)):
                lines.append(
                    f'{p}{{quantile="{q}"}} '
                    f"{_prom_num(m.percentile(pct))}")
            lines.append(f"{p}_sum {_prom_num(m.sum)}")
            lines.append(f"{p}_count {_prom_num(m.count)}")
    # collector-sourced flat values (snapshot keys beyond the typed set)
    for k, v in sorted(reg.snapshot().items()):
        if k in typed_names or k.startswith("_") \
                or not isinstance(v, (int, float)) \
                or isinstance(v, bool):
            continue
        p = _prom_name(k, prefix)
        lines.append(f"# TYPE {p} gauge")
        lines.append(f"{p} {_prom_num(v)}")
    return "\n".join(lines) + "\n"


def write_prometheus(path: str, reg=None, prefix: str = "sherman") -> str:
    """Atomic Prometheus textfile write (tmp + rename): the
    node-exporter textfile collector must never read a torn file."""
    text = prometheus_text(reg, prefix)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)
    return path


class MetricsServer:
    """Stdlib HTTP scrape endpoint: ``GET /metrics`` serves
    :func:`prometheus_text`; anything else 404s.  Daemon-threaded,
    binds once on :meth:`start` (``port=0`` picks a free port — the
    bound one is in ``.port``)."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1", reg=None,
                 prefix: str = "sherman"):
        self.host = host
        self.port = int(port)
        self.reg = reg if reg is not None else _registry.get_registry()
        self.prefix = prefix
        self._httpd = None
        self._thread: threading.Thread | None = None

    def start(self) -> "MetricsServer":
        assert self._httpd is None, "already started"
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.split("?")[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                try:
                    body = prometheus_text(server.reg,
                                           server.prefix).encode()
                except Exception as e:  # a raising collector mid-step
                    self.send_error(500, str(e))
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes must not spam stderr
                pass

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="obs-metrics")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        self._httpd = None
        self._thread = None

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def maybe_serve_http(env: str = METRICS_PORT_ENV,
                     reg=None) -> "MetricsServer | None":
    """Env-gated scrape endpoint: start a :class:`MetricsServer` when
    ``env`` holds a positive port, else None.  A malformed value raises
    (a typo on an exposition knob should be loud, not silently dark)."""
    raw = os.environ.get(env, "").strip()
    if not raw:
        return None
    try:
        port = int(raw)
    except ValueError:
        raise ConfigError(
            f"{env}={raw!r} is not a port number; set e.g. 9095, or "
            "unset it to disable the scrape endpoint") from None
    if port <= 0:
        return None
    return MetricsServer(port=port, reg=reg).start()
