"""Exporters: one-call JSON dumps and periodic JSONL snapshots.

``bench.py`` and the tools/ drivers report through here instead of
hand-formatting their own strings:

- :func:`obs_section` — the dict a driver embeds in its JSON output
  (``{"counters": ..., "spans": ...}``), built from the default
  registry + tracer.
- :func:`dump` — write a full observability dump (metrics snapshot +
  span summary + Chrome trace events) to one JSON file.
- :func:`write_snapshot_jsonl` / :class:`PeriodicExporter` — append
  timestamped registry snapshots to a JSONL file, manually or on a
  background interval (the long-churn drivers' flight recorder).
"""

from __future__ import annotations

import json
import os
import threading
import time

from sherman_tpu.obs import registry as _registry
from sherman_tpu.obs import spans as _spans

__all__ = ["dump", "obs_section", "write_snapshot_jsonl",
           "PeriodicExporter"]


def obs_section(reg=None, tracer=None) -> dict:
    """The ``obs`` dict drivers embed in their JSON output."""
    reg = reg if reg is not None else _registry.get_registry()
    tracer = tracer if tracer is not None else _spans.get_tracer()
    return {"counters": reg.snapshot(), "spans": tracer.summary()}


def dump(path: str, reg=None, tracer=None, *, extra: dict | None = None
         ) -> str:
    """Write metrics + spans + Chrome trace events to ``path`` (JSON).

    The file doubles as a Perfetto-loadable trace: ``traceEvents`` is
    top-level per the Chrome trace-event spec, with the metrics
    snapshot riding in ``otherData``.  Returns the path."""
    reg = reg if reg is not None else _registry.get_registry()
    tracer = tracer if tracer is not None else _spans.get_tracer()
    doc = tracer.chrome_trace()
    doc["otherData"].update({
        "metrics": reg.snapshot(),
        "span_summary": tracer.summary(),
        "wall_time": time.time(),
        **(extra or {}),
    })
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(doc, f)
    return path


def write_snapshot_jsonl(path: str, reg=None, *,
                         extra: dict | None = None) -> None:
    """Append one timestamped registry snapshot as a JSONL line."""
    reg = reg if reg is not None else _registry.get_registry()
    line = {"t": time.time(), "metrics": reg.snapshot(), **(extra or {})}
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "a") as f:
        f.write(json.dumps(line) + "\n")


class PeriodicExporter:
    """Background-thread JSONL snapshot writer.

    >>> ex = PeriodicExporter("obs.jsonl", interval_s=10.0)
    >>> ex.start()
    ...
    >>> ex.stop()   # writes one final snapshot

    Snapshots invoke registry collectors (which may touch device
    arrays); drivers whose collectors are not safe mid-step should
    snapshot manually at step boundaries instead.
    """

    def __init__(self, path: str, interval_s: float = 10.0, reg=None):
        self.path = path
        self.interval_s = interval_s
        self.reg = reg if reg is not None else _registry.get_registry()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "PeriodicExporter":
        assert self._thread is None, "already started"
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="obs-exporter")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            write_snapshot_jsonl(self.path, self.reg)

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=5.0)
        self._thread = None
        write_snapshot_jsonl(self.path, self.reg, extra={"final": True})

    def __enter__(self) -> "PeriodicExporter":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
