"""sherman_tpu.obs — the unified observability plane.

The reference Sherman has no observability layer (SURVEY.md §5):
profiling is a manual ns ``Timer`` plus hand-rolled latency histograms,
and op counters live inside ``DSM``.  This package is the single
instrumentation surface every layer reports through:

- :mod:`sherman_tpu.obs.registry` — process-wide metrics registry
  (counters, gauges, histograms) with snapshot/delta semantics so
  drivers and tests can diff op counts around a timed region.  Hot-path
  increments are a plain attribute add — no locks, no dict lookups.
- :mod:`sherman_tpu.obs.spans` — nested span tracing with thread-safe
  recording and Chrome-trace-event JSON export (loadable in
  ``chrome://tracing`` / Perfetto), absorbing the legacy
  :class:`StepTrace` micro-tracer.
- :mod:`sherman_tpu.obs.export` — JSONL periodic snapshots and the
  one-call :func:`~sherman_tpu.obs.export.dump` used by ``bench.py``.

Wired-in sources: the DSM registers its device op/byte counters as a
pull collector (``dsm.*`` keys in every snapshot), the transports count
collective builds and payload bytes, the batched engine wraps its
combine/descend/apply phases in spans, and the host B+Tree counts index
cache hits/misses/invalidations.
"""

from __future__ import annotations

from sherman_tpu.obs.export import dump, obs_section, write_snapshot_jsonl
from sherman_tpu.obs.registry import (Counter, Gauge, Histogram,
                                      MetricsRegistry, counter, delta, gauge,
                                      get_registry, histogram,
                                      register_collector, snapshot)
from sherman_tpu.obs.spans import (SpanTracer, StepTrace, device_trace,
                                   get_tracer, span)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "counter", "gauge", "histogram", "snapshot", "delta",
    "register_collector", "get_registry",
    "SpanTracer", "StepTrace", "device_trace", "get_tracer", "span",
    "dump", "obs_section", "write_snapshot_jsonl",
]
