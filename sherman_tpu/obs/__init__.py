"""sherman_tpu.obs — the unified observability plane.

The reference Sherman has no observability layer (SURVEY.md §5):
profiling is a manual ns ``Timer`` plus hand-rolled latency histograms,
and op counters live inside ``DSM``.  This package is the single
instrumentation surface every layer reports through:

- :mod:`sherman_tpu.obs.registry` — process-wide metrics registry
  (counters, gauges, histograms) with snapshot/delta semantics so
  drivers and tests can diff op counts around a timed region.  Hot-path
  increments are a plain attribute add — no locks, no dict lookups.
- :mod:`sherman_tpu.obs.spans` — nested span tracing with thread-safe
  recording and Chrome-trace-event JSON export (loadable in
  ``chrome://tracing`` / Perfetto), absorbing the legacy
  :class:`StepTrace` micro-tracer.
- :mod:`sherman_tpu.obs.slo` — the SLO telemetry layer: per-op-class
  (read/insert/delete/mixed/scan) amortized latency with sliding-window
  ops/s and p50/p99/p999, fed by every batch wall (engine entry points
  + the device-staged step factories' ``record_slo``).  Registered as
  the ``slo.*`` pull collector.
- :mod:`sherman_tpu.obs.recorder` — the black-box flight recorder: a
  bounded ring of structured events (chaos injections, lease
  revocations, degraded transitions, journal poisonings,
  recovery/repair steps, compile retraces, span closes) with env-gated
  auto-dump bundles (Chrome trace + events JSONL) on degraded entry,
  typed-error raise, watchdog fire, or steady-state retrace.
- :mod:`sherman_tpu.obs.device` — the white-box device-telemetry
  plane: the compile ledger (every jit compilation as a structured
  {program, shape signature, compile ms} entry, with the post-seal
  steady-state retrace detector), the HBM/live-buffer accountant
  (pool/journal/checkpoint byte gauges with a peak watermark,
  per-program ``memory_analysis``), and roofline receipts
  (``cost_analysis`` flops/bytes joined with measured phase walls into
  achieved-fraction-of-peak).  Registered as the ``device.`` pull
  collector beside ``slo.``; ``SHERMAN_DEVICE_OBS=0`` kills it.
- :mod:`sherman_tpu.obs.export` — JSONL periodic snapshots, the
  one-call :func:`~sherman_tpu.obs.export.dump` used by ``bench.py``,
  Prometheus text exposition (textfile mode + optional stdlib HTTP
  scrape endpoint behind ``SHERMAN_METRICS_PORT``).

Wired-in sources: the DSM registers its device op/byte counters as a
pull collector (``dsm.*`` keys in every snapshot), the transports count
collective builds and payload bytes, the batched engine wraps its
combine/descend/apply phases in spans AND attributes every host-path
batch wall to its op class, and the host B+Tree counts index cache
hits/misses/invalidations.
"""

from __future__ import annotations

from sherman_tpu.obs.device import (CompileLedger, MemoryAccountant,
                                    device_peaks, get_accountant,
                                    get_ledger, program_cost,
                                    program_memory, roofline, rooflines,
                                    wrap_program)
from sherman_tpu.obs.export import (MetricsServer, PeriodicExporter, dump,
                                    maybe_serve_http, obs_section,
                                    prometheus_text, write_prometheus,
                                    write_snapshot_jsonl)
from sherman_tpu.obs.recorder import (FlightRecorder, auto_dump,
                                      get_recorder, record_event)
from sherman_tpu.obs.registry import (Counter, Gauge, Histogram,
                                      MetricsRegistry, counter, delta, gauge,
                                      get_registry, histogram,
                                      register_collector, snapshot)
from sherman_tpu.obs.slo import (LatencyTracker, SloTracker, WindowedRate,
                                 get_slo, observe, observe_op, slo_window)
from sherman_tpu.obs.spans import (SpanTracer, StepTrace, device_trace,
                                   get_tracer, span)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "counter", "gauge", "histogram", "snapshot", "delta",
    "register_collector", "get_registry",
    "SpanTracer", "StepTrace", "device_trace", "get_tracer", "span",
    "dump", "obs_section", "write_snapshot_jsonl", "PeriodicExporter",
    "prometheus_text", "write_prometheus", "MetricsServer",
    "maybe_serve_http",
    "LatencyTracker", "WindowedRate", "SloTracker",
    "get_slo", "observe", "observe_op", "slo_window",
    "FlightRecorder", "get_recorder", "record_event", "auto_dump",
    "CompileLedger", "MemoryAccountant", "get_ledger", "get_accountant",
    "wrap_program", "program_cost", "program_memory", "roofline",
    "rooflines", "device_peaks",
]
