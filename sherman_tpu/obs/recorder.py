"""Black-box flight recorder: a bounded ring of structured events.

A chaos or recovery drill that goes wrong leaves scattered counters
behind — totals with no order.  This module keeps the ORDER: a bounded
ring buffer of recent structured events (span closes, chaos
injections, lease revocations, degraded-mode transitions, journal
fsync poisonings, recovery/repair steps), cheap enough to stay on
permanently (one deque append under a lock, at control-plane moments —
never per op), and dumped as a readable bundle when something breaks.

Event sources (each site calls :func:`record_event`):

- ``span``                      every default-tracer span close
  (completion is per *phase*, not per request — the tracer's own
  contract keeps this off the hot path)
- ``chaos.inject``              each fired fault (kind/step/addr)
- ``lease.revoked``             a dead holder's lock revoked
- ``scrub.violation`` / ``scrub.quarantine``
- ``engine.degraded_enter`` / ``engine.degraded_exit`` /
  ``engine.typed_error``
- ``journal.poisoned`` / ``journal.torn_tail``
- ``checkpoint.save`` / ``checkpoint.restore``
- ``recovery.checkpoint_base`` / ``recovery.checkpoint_delta`` /
  ``recovery.recover`` / ``recovery.targeted_repair`` /
  ``recovery.targeted_repair_failed``
- ``watchdog.fired``
- ``serve.start`` / ``serve.sealed`` / ``serve.width_change`` /
  ``serve.brownout_enter`` / ``serve.brownout_exit`` /
  ``serve.dispatch_error`` / ``serve.stop`` / ``serve.drain``
  (the serving front door's control-plane moments —
  sherman_tpu/serve.py)
- ``audit.violation`` / ``audit.checker_error``   (the client-contract
  linearizability auditor — sherman_tpu/audit.py; a violation also
  auto-dumps the black box, the degraded-entry contract)

Auto-dump: :func:`auto_dump` fires on degraded entry, typed-error
raise, and watchdog expiry — but only when ``SHERMAN_BLACKBOX_DIR``
names a directory (tests and libraries must not spray files), and
debounced to one dump per ``min_dump_interval_s`` unless forced (the
watchdog forces: it is about to kill the process).  A dump is a
two-file bundle:

- ``blackbox-<stamp>-<reason>.json`` — Perfetto-loadable Chrome trace
  (the default tracer's events) with the event ring, the full metrics
  snapshot and the span summary riding in ``otherData``;
- ``blackbox-<stamp>-<reason>.events.jsonl`` — the event ring alone,
  one JSON object per line (grep-able postmortem order).
"""

from __future__ import annotations

import json
import os
import threading
import time

from sherman_tpu.obs import registry as _registry
from sherman_tpu.errors import ConfigError
from sherman_tpu.obs import spans as _spans

__all__ = ["FlightRecorder", "get_recorder", "record_event", "auto_dump",
           "BLACKBOX_ENV"]

BLACKBOX_ENV = "SHERMAN_BLACKBOX_DIR"


class FlightRecorder:
    """Bounded, thread-safe ring of (seq, t, kind, fields) events."""

    def __init__(self, capacity: int = 4096,
                 min_dump_interval_s: float = 5.0):
        from collections import deque
        self.capacity = int(capacity)
        self.min_dump_interval_s = float(min_dump_interval_s)
        self._lock = threading.Lock()
        self._ring = deque(maxlen=self.capacity)
        self._seq = 0
        self._dumps = 0
        self._last_dump = -1e18
        self.dropped = 0  # events evicted by the ring bound

    def record(self, kind: str, **fields) -> int:
        """Append one event; returns its sequence number (global order
        even across ring eviction)."""
        t = time.time()
        with self._lock:
            self._seq += 1
            seq = self._seq
            if len(self._ring) == self.capacity:
                self.dropped += 1
            self._ring.append((seq, t, kind, fields or None))
        return seq

    def events(self) -> list[dict]:
        with self._lock:
            ring = list(self._ring)
        return [{"seq": seq, "t": t, "kind": kind,
                 **({"fields": fields} if fields else {})}
                for seq, t, kind, fields in ring]

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self.dropped = 0

    # -- dumping --------------------------------------------------------------

    def dump(self, reason: str, directory: str | None = None) -> str:
        """Write the bundle (see module docstring); returns the path of
        the ``.json`` trace file.  ``directory`` defaults to
        ``$SHERMAN_BLACKBOX_DIR`` and must resolve to something."""
        directory = directory or os.environ.get(BLACKBOX_ENV)
        if not directory:
            raise ConfigError(
                f"flight-recorder dump needs a directory ({BLACKBOX_ENV} "
                "unset and none passed)")
        os.makedirs(directory, exist_ok=True)
        with self._lock:
            self._dumps += 1
            n = self._dumps
            self._last_dump = time.monotonic()
        safe = "".join(c if c.isalnum() or c in "-_" else "_"
                       for c in reason)[:48]
        stamp = time.strftime("%Y%m%d-%H%M%S")
        stem = os.path.join(directory, f"blackbox-{stamp}-{n:03d}-{safe}")
        events = self.events()
        tracer = _spans.get_tracer()
        doc = tracer.chrome_trace()
        doc["otherData"].update({
            "reason": reason,
            "wall_time": time.time(),
            "flight_events": events,
            "flight_dropped": self.dropped,
            "metrics": _registry.snapshot(),
            "span_summary": tracer.summary(),
        })
        with open(stem + ".json", "w") as f:
            json.dump(doc, f)
        with open(stem + ".events.jsonl", "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        return stem + ".json"

    def auto_dump(self, trigger: str, *, force: bool = False) -> str | None:
        """Env-gated, debounced dump — the degraded-entry / typed-error
        / watchdog hook.  None when the env knob is unset or the
        debounce window has not elapsed (a degraded engine raising
        DegradedError per rejected write must not dump per raise)."""
        directory = os.environ.get(BLACKBOX_ENV)
        if not directory:
            return None
        if not force:
            with self._lock:
                if (time.monotonic() - self._last_dump
                        < self.min_dump_interval_s):
                    return None
        try:
            return self.dump(trigger, directory)
        except OSError:
            return None  # a full/readonly disk must not take serving down


# -- process-wide default recorder --------------------------------------------

_RECORDER = FlightRecorder(
    capacity=int(os.environ.get("SHERMAN_BLACKBOX_EVENTS", 4096)))


def get_recorder() -> FlightRecorder:
    return _RECORDER


def record_event(kind: str, **fields) -> int:
    return _RECORDER.record(kind, **fields)


def auto_dump(trigger: str, *, force: bool = False) -> str | None:
    return _RECORDER.auto_dump(trigger, force=force)


def _span_close(name: str, dur_s: float, depth: int) -> None:
    _RECORDER.record("span", name=name, dur_ms=round(dur_s * 1e3, 3),
                     depth=depth)


# subscribe the default recorder to the default tracer's span closes
# (per-phase, not per-op — see the SpanTracer docstring)
_spans.get_tracer().on_close = _span_close
