"""White-box device telemetry: compile ledger, HBM accounting, rooflines.

The SLO plane (:mod:`sherman_tpu.obs.slo`) measures the system from the
OUTSIDE — per-class walls and windowed rates — but attributes nothing to
the compiled programs that produce those walls.  Sherman's performance
argument is that every op is a fixed number of one-sided reads/writes
against known page layouts (PAPER.md §4-5), so each serve program has a
*computable* byte/flop floor; this module publishes it, plus the two
device-side hazards no black-box gauge can see:

- **Compile ledger** (:class:`CompileLedger`): every jit compilation is
  recorded as a structured entry ``{program label, abstract-shape
  signature, compile ms, count}``.  Compiles are observed two ways at
  once: a ``jax.monitoring`` duration listener (the
  ``backend_compile`` events, present on this 0.4.37 toolchain)
  attributes compile *walls* to the program whose dispatch triggered
  them, and a per-program wrapper (:meth:`CompileLedger.wrap`, applied
  at the engine/staged jit-cache sites) detects the compile itself via
  the jit cache-size delta — the fallback that keeps detection working
  on toolchains where the event names are absent.  The **steady-state
  retrace detector**: after :meth:`CompileLedger.seal` (bench.py's
  ``run_windowed`` seals around every timed device-step window), ANY
  new compilation increments ``device.retraces``, emits a
  ``compile.retrace`` flight-recorder event, and auto-dumps the black
  box (env-gated + debounced, the degraded-entry contract) — the
  classic silent-retrace serving hazard becomes a red CI pin instead
  of a mystery p99 cliff.
- **HBM / live-buffer accountant** (:class:`MemoryAccountant`):
  weakref-bound byte sources (the DSM registers its pool/locks/
  counters, the journal and recovery plane their on-disk artifacts)
  published as ``device.hbm_*`` / ``device.host_*`` gauges with a peak
  watermark, plus per-program :func:`program_memory` —
  ``compiled.memory_analysis()`` through the AOT path, gracefully
  degrading to a typed ``{"available": False, "reason": ...}`` where
  the backend cannot answer.
- **Roofline receipts**: :func:`program_cost` (flops/bytes from
  ``lowered.cost_analysis()`` — no second backend compile) joined with
  a measured phase wall by :func:`roofline` into
  ``achieved_bytes_frac`` / ``achieved_flops_frac`` against the
  device's peak HBM bandwidth and peak flops
  (:func:`device_peaks`: known TPU generations by ``device_kind``,
  overridable via ``SHERMAN_PEAK_GBPS`` / ``SHERMAN_PEAK_TFLOPS``;
  unknown backends publish absolute achieved rates and leave the
  fractions out rather than invent a peak).

Process-wide default: :func:`get_ledger` / :func:`get_accountant`
register the ``device.`` pull collector on first access, so every
registry snapshot (and the Prometheus exposition) carries flat
``device.<stat>`` keys.  ``SHERMAN_DEVICE_OBS=0`` is the kill switch —
checked per dispatch, so the obs-on/off A/B needs no rebuild (the
wrapper then forwards straight to the program; the ledger goes dark).

Analysis compiles are **suppressed**: :func:`program_cost` /
:func:`program_memory` re-lower (and for memory, re-compile) through
the AOT path, which fires the same monitoring events as a real compile
— the suppression scope keeps the white-box instrument from reading
its own probe as a steady-state retrace.
"""

from __future__ import annotations

import os
import threading
import time

from sherman_tpu.obs import recorder as _recorder
from sherman_tpu.obs import registry as _registry

__all__ = [
    "DEVICE_OBS_ENV", "CompileLedger", "LedgeredProgram",
    "MemoryAccountant", "program_cost", "program_memory", "roofline",
    "rooflines", "device_peaks", "get_ledger", "get_accountant",
    "wrap_program", "enabled",
]

DEVICE_OBS_ENV = "SHERMAN_DEVICE_OBS"

# the jax.monitoring event that marks a real backend compile on this
# toolchain (/jax/core/compile/backend_compile_duration); tracing and
# MLIR-lowering events deliberately do NOT count — only the executable
# build is the retrace hazard's cost
_COMPILE_EVENT_TOKEN = "backend_compile"

# label charged for compiles the listener sees OUTSIDE any wrapped
# program's dispatch (host-API one-offs, third-party jits)
UNATTRIBUTED = "<unattributed>"


def enabled() -> bool:
    """The kill switch, checked per dispatch (one dict lookup) so the
    obs-on/off A/B toggles at runtime without rebuilding programs."""
    return os.environ.get(DEVICE_OBS_ENV, "1") != "0"


def _signature(args, kwargs=None) -> str:
    """Abstract-shape signature of a call: dtype[shape] per array leaf,
    the repr of everything else.  Computed only when a compile was
    detected — never on the per-dispatch hot path."""
    import jax

    parts = []
    leaves = jax.tree_util.tree_leaves((args, kwargs or {}))
    for a in leaves:
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None and dtype is not None:
            parts.append(f"{jax.numpy.dtype(dtype).name}"
                         f"[{','.join(str(d) for d in shape)}]")
        else:
            parts.append(repr(a))
    return ",".join(parts)


def _abstractify(args):
    """Args -> ShapeDtypeStruct pytree for AOT re-lowering (analysis
    must not pin device buffers); non-array leaves pass through."""
    import jax

    def one(a):
        shape = getattr(a, "shape", None)
        dtype = getattr(a, "dtype", None)
        if shape is not None and dtype is not None:
            return jax.ShapeDtypeStruct(tuple(shape), dtype)
        return a

    return jax.tree_util.tree_map(one, args)


class _ProgramEntry:
    """One (label)'s ledger row: compile count/walls, the signatures
    that compiled, and the retraces charged to it post-seal."""

    __slots__ = ("label", "compiles", "compile_ms", "retraces",
                 "signatures", "avals", "fn_ref", "last_compile_t")

    def __init__(self, label: str):
        self.label = label
        self.compiles = 0
        self.compile_ms = 0.0
        self.retraces = 0
        self.signatures: dict[str, int] = {}   # sig -> compile count
        self.avals = None          # arg avals of the LAST compile
        self.fn_ref = None         # weakref to the jitted program
        self.last_compile_t = 0.0

    def snapshot(self) -> dict:
        return {
            "label": self.label,
            "compiles": self.compiles,
            "compile_ms": round(self.compile_ms, 3),
            "retraces": self.retraces,
            "signatures": dict(self.signatures),
        }


class LedgeredProgram:
    """Transparent wrapper around one jitted program: forwards every
    call (attributes, hashes and donation untouched — ``__getattr__``
    delegates), detects compiles via the jit cache-size delta, and
    reports them to the ledger with this program's label.  Cache the
    WRAPPER at the jit-cache site so program-identity pins
    (``step.jserve is eng._get_search_fanout(...)``) keep holding."""

    __slots__ = ("_fn", "label", "_ledger", "__weakref__")

    def __init__(self, ledger: "CompileLedger", label: str, fn):
        self._fn = fn
        self.label = label
        self._ledger = ledger

    @property
    def unwrapped(self):
        return self._fn

    def _cache_size(self):
        f = getattr(self._fn, "_cache_size", None)
        if f is None:
            return None
        try:
            return f()
        except Exception:
            return None

    def __call__(self, *args, **kwargs):
        led = self._ledger
        if not enabled():
            return self._fn(*args, **kwargs)
        n0 = self._cache_size()
        tok = led._enter(self.label)
        try:
            return self._fn(*args, **kwargs)
        finally:
            # detection runs even when the dispatch raises — a retraced
            # program that then fails is exactly the postmortem the
            # ledger exists for, and the monitoring events were already
            # credited to this frame
            ms, events = led._exit(tok)
            n1 = self._cache_size()
            # primary detection: the jit cache grew; fallback (no
            # _cache_size on this toolchain): a backend-compile event
            # landed inside this dispatch
            if (n1 is not None and n0 is not None and n1 > n0) \
                    or (n1 is None and events > 0):
                led._record_compile(self.label, ms, args, kwargs,
                                    self._fn)

    def __getattr__(self, name):
        return getattr(self._fn, name)

    def __repr__(self):
        return f"LedgeredProgram({self.label!r}, {self._fn!r})"


class CompileLedger:
    """Structured record of every observed jit compilation, with the
    post-``seal()`` steady-state retrace detector (module docstring).

    Thread model: entries mutate under one lock (compiles are rare);
    the per-dispatch cost when nothing compiles is a thread-local
    push/pop and one ``_cache_size()`` call.  The monitoring listener
    is process-wide and registered once (jax offers no unregister that
    spares other listeners), so :meth:`reset` zeroes state in place.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: dict[str, _ProgramEntry] = {}
        self._tls = threading.local()
        self._sealed = 0          # nesting depth of seal() scopes
        self.retraces = 0
        self.seals = 0
        self._attached = False
        self._listener_live = [False]  # probed: events actually arrive

    # -- dispatch context (wrapper + listener attribution) -------------------

    def _enter(self, label: str):
        st = self._tls
        stack = getattr(st, "stack", None)
        if stack is None:
            stack = st.stack = []
        frame = {"label": label, "ms": 0.0, "events": 0}
        stack.append(frame)
        return frame

    def _exit(self, frame) -> tuple[float, int]:
        st = self._tls
        stack = getattr(st, "stack", ())
        if stack and stack[-1] is frame:
            stack.pop()
        return frame["ms"], frame["events"]

    def _suppressed(self) -> bool:
        return getattr(self._tls, "suppress", 0) > 0

    class _Suppress:
        def __init__(self, ledger):
            self._l = ledger

        def __enter__(self):
            tls = self._l._tls
            tls.suppress = getattr(tls, "suppress", 0) + 1

        def __exit__(self, *exc):
            self._l._tls.suppress -= 1

    def suppress(self):
        """Scope in which compiles are the instrument's own (AOT
        analysis) and must not be recorded — least of all as
        retraces."""
        return self._Suppress(self)

    # -- jax.monitoring listener ---------------------------------------------

    def attach(self) -> str:
        """Register the duration listener once; returns the active
        compile-detection source.  First registration reports
        ``"monitoring"`` optimistically; later calls report it only
        once a backend-compile event has ACTUALLY arrived — on a
        toolchain where jax.monitoring imports but the event name
        changed, the end-of-run ``compile_source`` honestly reads
        ``"wrapper"`` (cache-size detection, walls unattributed)
        instead of claiming attribution that never happened."""
        with self._lock:
            if self._attached:
                return "monitoring" if self._listener_live[0] else "wrapper"
            try:
                from jax import monitoring
                monitoring.register_event_duration_secs_listener(
                    self._on_duration)
                self._attached = True
                return "monitoring"
            except Exception:
                self._attached = True
                return "wrapper"

    def _on_duration(self, name: str, dur_s: float, **kw) -> None:
        if _COMPILE_EVENT_TOKEN not in name:
            return
        # the liveness probe: this toolchain's event names match
        self._listener_live[0] = True
        if not enabled():
            return
        if self._suppressed():
            return
        ms = dur_s * 1e3
        stack = getattr(self._tls, "stack", ())
        if stack:
            # inside a wrapped dispatch: the wrapper will record the
            # compile (with signature) when the call returns
            stack[-1]["ms"] += ms
            stack[-1]["events"] += 1
            return
        # outside any wrapped program: record here so NOTHING compiles
        # invisibly — the post-seal case is exactly the silent retrace
        self._record_compile(UNATTRIBUTED, ms, None, None, None)

    # -- recording -----------------------------------------------------------

    def _record_compile(self, label: str, ms: float, args, kwargs,
                        fn) -> None:
        if self._suppressed():
            return
        sig = _signature(args, kwargs) if args is not None else "?"
        with self._lock:
            e = self._entries.get(label)
            if e is None:
                e = self._entries[label] = _ProgramEntry(label)
            e.compiles += 1
            e.compile_ms += ms
            e.signatures[sig] = e.signatures.get(sig, 0) + 1
            e.last_compile_t = time.monotonic()
            if args is not None:
                try:
                    e.avals = (_abstractify(args),
                               _abstractify(kwargs or {}))
                except Exception:
                    e.avals = None
            if fn is not None:
                import weakref
                try:
                    e.fn_ref = weakref.ref(fn)
                except TypeError:
                    e.fn_ref = None
            tripped = self._sealed > 0
            if tripped:
                e.retraces += 1
                self.retraces += 1
        if tripped:
            # the serving hazard: a compile inside a sealed steady-state
            # window.  Flight event + env-gated debounced black-box dump
            # (the degraded-entry contract) — postmortems start from the
            # program and shape that retraced.
            _recorder.record_event("compile.retrace", program=label,
                                   signature=sig,
                                   compile_ms=round(ms, 3))
            _recorder.auto_dump("compile_retrace")

    # -- seal / steady state --------------------------------------------------

    def seal(self) -> None:
        """Enter steady state: warmup/drain is done, every program this
        loop dispatches has compiled — from here until :meth:`unseal`,
        ANY observed compilation is a retrace.  Nests (scopes stack)."""
        with self._lock:
            self._sealed += 1
            self.seals += 1

    def unseal(self) -> None:
        with self._lock:
            if self._sealed > 0:
                self._sealed -= 1

    @property
    def sealed(self) -> bool:
        return self._sealed > 0

    class _Sealed:
        def __init__(self, ledger):
            self._l = ledger

        def __enter__(self):
            self._l.seal()
            return self._l

        def __exit__(self, *exc):
            self._l.unseal()

    def sealed_scope(self):
        """``with ledger.sealed_scope(): <timed loop>`` — the bench
        run_windowed shape."""
        return self._Sealed(self)

    # -- wrapping -------------------------------------------------------------

    def wrap(self, label: str, fn):
        """Wrap a jitted program for the ledger.  Idempotent on an
        already-wrapped program (re-labeling would split its history)."""
        if isinstance(fn, LedgeredProgram):
            return fn
        return LedgeredProgram(self, label, fn)

    # -- views ----------------------------------------------------------------

    def entries(self) -> list[dict]:
        with self._lock:
            return [e.snapshot() for e in self._entries.values()]

    def entry(self, label: str) -> _ProgramEntry | None:
        with self._lock:
            return self._entries.get(label)

    def summary(self) -> dict:
        """The bench-JSON ledger block: totals + per-program entries."""
        with self._lock:
            entries = [e.snapshot() for e in self._entries.values()]
        return {
            "programs": len(entries),
            "compiles": sum(e["compiles"] for e in entries),
            "compile_ms_total": round(
                sum(e["compile_ms"] for e in entries), 3),
            "retraces": self.retraces,
            "sealed_windows": self.seals,
            "entries": sorted(entries, key=lambda e: -e["compile_ms"]),
        }

    def collect(self) -> dict:
        """Flat numbers for the ``device.`` pull collector."""
        with self._lock:
            n = len(self._entries)
            compiles = sum(e.compiles for e in self._entries.values())
            ms = sum(e.compile_ms for e in self._entries.values())
        return {
            "programs": n,
            "compiles": compiles,
            "compile_ms_total": round(ms, 3),
            "retraces": self.retraces,
            "sealed": int(self._sealed > 0),
        }

    def analyze(self, label: str, *, memory: bool = False) -> dict:
        """Cost (and optionally memory) analysis of a ledgered program
        from its captured compile-time avals — no arg plumbing at the
        call site.  Typed-unavailable when the program never compiled
        under the ledger or the backend cannot answer."""
        e = self.entry(label)
        if e is None:
            return {"available": False,
                    "reason": f"no ledger entry for {label!r}"}
        fn = e.fn_ref() if e.fn_ref is not None else None
        if fn is None or e.avals is None:
            return {"available": False,
                    "reason": f"{label!r}: program or avals not captured"}
        args, kwargs = e.avals
        out = program_cost(fn, *args, _ledger=self, **kwargs)
        if memory:
            out["memory"] = program_memory(fn, *args, _ledger=self,
                                           **kwargs)
        return out

    def reset(self) -> None:
        """Zero in place (test isolation); the process-wide listener
        registration and any wrapped programs stay live."""
        with self._lock:
            self._entries.clear()
            self.retraces = 0
            self.seals = 0
            self._sealed = 0


# -- per-program analysis (AOT path, suppressed) ------------------------------

def _unwrap(fn):
    return fn.unwrapped if isinstance(fn, LedgeredProgram) else fn


def program_cost(fn, *args, _ledger=None, **kwargs) -> dict:
    """flops/bytes of one program via ``lowered.cost_analysis()`` (no
    second backend compile).  Graceful: any failure returns the typed
    ``{"available": False, "reason": ...}`` instead of raising — the
    receipts column reads "unavailable", the run does not die."""
    led = _ledger or get_ledger()
    try:
        with led.suppress():
            low = _unwrap(fn).lower(*args, **kwargs)
            ca = low.cost_analysis()
        if isinstance(ca, (list, tuple)):   # per-partition form
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0) or 0.0)
        bytes_ = float(ca.get("bytes accessed", 0.0) or 0.0)
        return {"available": True, "flops": flops, "bytes": bytes_}
    except Exception as e:
        return {"available": False,
                "reason": f"{type(e).__name__}: {e}"}


def program_memory(fn, *args, _ledger=None, **kwargs) -> dict:
    """``compiled.memory_analysis()`` through the AOT path (this DOES
    pay a backend compile — the persistent compilation cache absorbs it
    on repeat runs).  Graceful typed-unavailable on backends that
    cannot answer."""
    led = _ledger or get_ledger()
    try:
        with led.suppress():
            m = _unwrap(fn).lower(*args, **kwargs).compile() \
                           .memory_analysis()
        out = {"available": True}
        for k in ("generated_code_size_in_bytes",
                  "argument_size_in_bytes", "output_size_in_bytes",
                  "alias_size_in_bytes", "temp_size_in_bytes"):
            v = getattr(m, k, None)
            if v is not None:
                out[k.replace("_size_in_bytes", "_bytes")] = int(v)
        return out
    except Exception as e:
        return {"available": False,
                "reason": f"{type(e).__name__}: {e}"}


# -- rooflines ----------------------------------------------------------------

# peak (HBM bytes/s, flops/s) by TPU device_kind substring — the roofline
# ceilings fractions are computed against.  Sources: published TPU specs
# (bf16 peak flops; HBM BW).  Env overrides win (SHERMAN_PEAK_GBPS /
# SHERMAN_PEAK_TFLOPS) so a new device kind needs no code change.
_KNOWN_PEAKS = (
    ("v5p", 2765e9, 459e12),
    ("v5 lite", 819e9, 197e12),  # libtpu reports v5e as "TPU v5 lite"
    ("v5e", 819e9, 197e12),
    ("v6 lite", 1640e9, 918e12),  # ... and v6e/Trillium as "TPU v6 lite"
    ("v6e", 1640e9, 918e12),
    ("v4", 1228e9, 275e12),
    ("v3", 900e9, 123e12),
    ("v2", 700e9, 45e12),
)


def device_peaks() -> dict:
    """{"bytes_per_s", "flops_per_s", "source"} for device 0 — each
    peak resolves independently: a valid env override wins, otherwise
    the known-TPU table (so overriding just the bandwidth on a known
    part keeps the table's flops roof); a malformed override is flagged
    in ``source`` and falls back like an unset one — this only runs at
    end-of-run section build, after all the timed windows, and a typo
    must not cost the run its receipt.  Unknown backends (this CPU
    mesh) leave unresolved peaks None so fractions are omitted, never
    invented."""
    notes = []

    def _env(var: str, scale: float):
        raw = os.environ.get(var)
        if not raw:
            return None
        try:
            return float(raw) * scale
        except ValueError:
            notes.append(f"bad-env:{var}")
            return None

    bw = _env("SHERMAN_PEAK_GBPS", 1e9)
    fl = _env("SHERMAN_PEAK_TFLOPS", 1e12)
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        kind = ""
    table = next(((tbw, tfl) for token, tbw, tfl in _KNOWN_PEAKS
                  if token in kind), None)
    if bw is not None or fl is not None:
        notes.append("env")
    if table is not None and (bw is None or fl is None):
        notes.append(f"device_kind:{kind}")
        bw = table[0] if bw is None else bw
        fl = table[1] if fl is None else fl
    elif table is None and (bw is None or fl is None):
        notes.append(f"unknown:{kind or 'no-device'}")
    return {"bytes_per_s": bw, "flops_per_s": fl,
            "source": ";".join(notes)}


def roofline(cost: dict, wall_ms: float, peaks: dict | None = None) -> dict:
    """Join one program's flop/byte floor with its measured wall:
    achieved rates always, achieved FRACTIONS only when the device's
    peaks are known (``achieved_bytes_frac`` = achieved bytes/s over
    peak HBM bandwidth — Sherman's serve phases should live near the
    bytes roof, which is the whole paper's §4-5 claim made auditable).
    Typed-unavailable cost dicts pass through with the wall attached."""
    out = {"wall_ms": round(float(wall_ms), 3)}
    if not cost.get("available"):
        out["available"] = False
        out["reason"] = cost.get("reason", "cost analysis unavailable")
        return out
    wall_s = max(float(wall_ms), 1e-6) / 1e3
    flops, bytes_ = cost["flops"], cost["bytes"]
    out.update({
        "available": True,
        "flops": flops,
        "bytes": bytes_,
        "achieved_gbytes_s": round(bytes_ / wall_s / 1e9, 3),
        "achieved_gflops_s": round(flops / wall_s / 1e9, 3),
    })
    # a wall under the chained-delta resolution (~50 us) makes the
    # achieved rates measurement noise — keep them (flagged) but never
    # publish FRACTIONS from them: a noise-phase frac would whipsaw the
    # perfgate bytes-frac comparison round to round
    if float(wall_ms) < 0.05:
        out["wall_below_resolution"] = True
        return out
    peaks = peaks or device_peaks()
    pb, pf = peaks.get("bytes_per_s"), peaks.get("flops_per_s")
    if pb:
        out["achieved_bytes_frac"] = round(bytes_ / wall_s / pb, 4)
    if pf:
        out["achieved_flops_frac"] = round(flops / wall_s / pf, 4)
    if pb and pf:
        # which roof binds this program (its arithmetic intensity vs
        # the machine balance point)
        t_bytes = bytes_ / pb
        t_flops = flops / pf
        out["bound"] = "bytes" if t_bytes >= t_flops else "flops"
    return out


def rooflines(phase_ms: dict, phase_labels: dict, *,
              memory: bool = False, peaks: dict | None = None,
              ledger: "CompileLedger | None" = None) -> dict:
    """Per-phase roofline receipts: join a ``phase_profile``-shaped
    ``{phase: wall_ms}`` dict with the ledger entries named by
    ``phase_labels`` (``step.phase_labels`` on the staged factories).
    Phases without a label (the pipelined overlap-receipt keys) are
    skipped; unanalyzable programs carry the typed unavailable."""
    led = ledger or get_ledger()
    peaks = peaks or device_peaks()
    out = {}
    for phase, ms in phase_ms.items():
        label = phase_labels.get(phase)
        if label is None or not isinstance(ms, (int, float)):
            continue
        ana = led.analyze(label, memory=memory)
        rec = roofline(ana, ms, peaks)
        rec["program"] = label
        if memory and "memory" in ana:
            rec["memory"] = ana["memory"]
        out[phase] = rec
    return out


# -- memory accountant --------------------------------------------------------

class MemoryAccountant:
    """Named live-byte sources with a peak watermark.

    Sources are weakref-bound at the call sites (a dead DSM's pool must
    drop out, not pin device arrays); a source that raises reports 0
    for that snapshot (donated buffer mid-step — the registry
    collector-error contract).  ``kind`` splits the exposition:
    ``hbm`` sources are device-resident buffers (pool/locks/counters),
    ``host`` sources are host-side artifacts (journal, checkpoints).
    The watermark tracks the max TOTAL hbm bytes any snapshot saw."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sources: dict[str, tuple[str, object]] = {}
        self.hbm_peak_bytes = 0

    def register(self, name: str, fn, *, kind: str = "hbm") -> None:
        """``fn() -> bytes``; re-registering a name replaces it (a
        rotated journal segment supersedes its ancestor)."""
        assert kind in ("hbm", "host"), kind
        with self._lock:
            self._sources[name] = (kind, fn)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._sources.pop(name, None)

    def gauges(self) -> dict:
        """Flat ``{hbm_<name>_bytes, host_<name>_bytes, ...,
        hbm_total_bytes, hbm_peak_bytes}``; updates the watermark."""
        with self._lock:
            sources = list(self._sources.items())
        out: dict = {}
        hbm_total = 0
        for name, (kind, fn) in sources:
            try:
                v = int(fn())
            except Exception:
                v = 0
            out[f"{kind}_{name}_bytes"] = v
            if kind == "hbm":
                hbm_total += v
        out["hbm_total_bytes"] = hbm_total
        with self._lock:
            if hbm_total > self.hbm_peak_bytes:
                self.hbm_peak_bytes = hbm_total
            out["hbm_peak_bytes"] = self.hbm_peak_bytes
        return out

    def reset(self) -> None:
        with self._lock:
            self._sources.clear()
            self.hbm_peak_bytes = 0


# -- process-wide defaults ----------------------------------------------------

_LEDGER = CompileLedger()
_ACCOUNTANT = MemoryAccountant()
_REGISTERED = [False]


def _collect() -> dict:
    if not enabled():
        return {"enabled": 0}
    out = _LEDGER.collect()
    out.update(_ACCOUNTANT.gauges())
    out["enabled"] = 1
    return out


def _register() -> None:
    if not _REGISTERED[0]:
        _registry.register_collector("device", _collect)
        _REGISTERED[0] = True


def get_ledger() -> CompileLedger:
    """The default ledger, listener attached and registered as (half
    of) the ``device.`` pull collector on first access."""
    _register()
    if enabled():
        _LEDGER.attach()
    return _LEDGER


def get_accountant() -> MemoryAccountant:
    _register()
    return _ACCOUNTANT


def wrap_program(label: str, fn):
    """Module-level convenience for the jit-cache sites:
    ``fn = device.wrap_program("engine.search", jax.jit(...))``."""
    return get_ledger().wrap(label, fn)
