"""Native runtime ring — C++ components behind a ctypes C ABI.

The reference is 100% native C++ (SURVEY.md §2); this package holds the TPU
build's native equivalents for everything host-side on the hot path but
outside the XLA data plane:

- ``ZipfGen``          — workload generator (test/zipf.h role)
- ``LatencyHistogram`` — 0.1 µs-bucket latency histogram + percentiles
                         (Tree.cpp:17 / benchmark.cpp:207-249 role)
- ``SkipList``         — concurrent skiplist (third_party/inlineskiplist.h
                         role; standalone skiplist_test parity)
- ``IndexCache``       — range -> leaf-addr cache with CAS invalidation,
                         delay-free epochs, 2-random eviction, hit stats
                         (include/IndexCache.h role)
- ``LocalLockTable``   — ticket locks with bounded hand-over
                         (Tree.cpp:1124-1173 role)

Built on first import with ``g++`` into ``build/libsherman_native.so``
(rebuilt when any source is newer).  ``available()`` reports whether the
library loaded; callers keep pure-Python fallbacks where one exists.
"""

from __future__ import annotations

import ctypes as ct
import os
import subprocess
import tempfile

import numpy as np

from sherman_tpu.errors import (ConfigError, NativeBuildError,
                                NativeUnavailableError, ShermanError)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src")
_BUILD = os.path.join(_DIR, "build")
_LIB = os.path.join(_BUILD, "libsherman_native.so")

_lib = None
_load_error: str | None = None


def _sources() -> list[str]:
    return sorted(
        os.path.join(_SRC, f) for f in os.listdir(_SRC) if f.endswith(".cc"))


def _stale() -> bool:
    if not os.path.exists(_LIB):
        return True
    t = os.path.getmtime(_LIB)
    deps = _sources() + [
        os.path.join(_SRC, f) for f in os.listdir(_SRC) if f.endswith(".h")]
    return any(os.path.getmtime(s) > t for s in deps)


def _build() -> None:
    os.makedirs(_BUILD, exist_ok=True)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_BUILD)
    os.close(fd)
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
           "-fvisibility=hidden", "-o", tmp] + _sources()
    try:
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, _LIB)  # atomic under concurrent builders
    except subprocess.CalledProcessError as e:
        raise NativeBuildError(f"native build failed:\n{e.stderr}") from e
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _sig(name: str, res, args) -> None:
    fn = getattr(_lib, name)
    fn.restype = res
    fn.argtypes = args
    globals()["_" + name] = fn


def _load() -> None:
    global _lib, _load_error
    if _lib is not None or _load_error is not None:
        return
    try:
        if _stale():
            _build()
        _lib = ct.CDLL(_LIB)
    except (OSError, RuntimeError) as e:  # no g++ / bad toolchain
        _load_error = str(e)
        return
    P, U64, I32, F64 = ct.c_void_p, ct.c_uint64, ct.c_int, ct.c_double
    PU64, PF64 = ct.POINTER(ct.c_uint64), ct.POINTER(ct.c_double)
    _sig("shn_zipf_new", P, [U64, F64, U64])
    _sig("shn_zipf_fill", None, [P, PU64, U64])
    _sig("shn_zipf_free", None, [P])
    _sig("shn_hist_new", P, [])
    _sig("shn_hist_free", None, [P])
    _sig("shn_hist_reset", None, [P])
    _sig("shn_hist_record", None, [P, U64])
    _sig("shn_hist_record_many", None, [P, PU64, U64])
    _sig("shn_hist_record_batch", None, [P, U64, U64])
    _sig("shn_hist_count", U64, [P])
    _sig("shn_hist_percentiles", None, [P, PF64, U64, PF64])
    _sig("shn_skl_new", P, [U64])
    _sig("shn_skl_free", None, [P])
    _sig("shn_skl_insert", I32, [P, U64, U64])
    _sig("shn_skl_seek_ge", I32, [P, U64, PU64, PU64])
    _sig("shn_skl_count", U64, [P])
    _sig("shn_cache_new", P, [U64])
    _sig("shn_cache_free", None, [P])
    _sig("shn_cache_add", I32, [P, U64, U64, U64])
    _sig("shn_cache_add_many", None, [P, PU64, PU64, PU64, U64])
    _sig("shn_cache_lookup", U64, [P, U64])
    _sig("shn_cache_lookup_many", None, [P, PU64, U64, PU64])
    _sig("shn_cache_invalidate", I32, [P, U64])
    _sig("shn_cache_stats", None, [P, PU64])
    _sig("shn_lt_new", P, [U64])
    _sig("shn_lt_free", None, [P])
    _sig("shn_lt_acquire", I32, [P, U64])
    _sig("shn_lt_can_handover", I32, [P, U64])
    _sig("shn_lt_release", I32, [P, U64, I32])
    I64, PI32, PU8 = ct.c_int64, ct.POINTER(ct.c_int32), ct.POINTER(ct.c_uint8)
    _sig("shn_prep_new", P, [U64, F64, U64, U64, U64, U64])
    _sig("shn_prep_free", None, [P])
    _sig("shn_prep_run_keys", I64,
         [P, PU64, U64, PI32, U64, ct.c_uint32, ct.c_int32,
          PI32, PI32, PI32, PU8, PI32])
    _sig("shn_prep_run_zipf", I64,
         [P, PU64, PU64, PI32, U64, ct.c_uint32, ct.c_int32,
          PI32, PI32, PI32, PU8, PI32])
    _sig("shn_rw_new", P, [])
    _sig("shn_rw_free", None, [P])
    _sig("shn_rw_rlock", None, [P])
    _sig("shn_rw_runlock", None, [P])
    _sig("shn_rw_wlock", None, [P])
    _sig("shn_rw_wunlock", None, [P])


def available() -> bool:
    _load()
    return _lib is not None


def load_error() -> str | None:
    _load()
    return _load_error


def _u64p(a: np.ndarray):
    return a.ctypes.data_as(ct.POINTER(ct.c_uint64))


def _require() -> None:
    if not available():
        raise NativeUnavailableError(f"native library unavailable: {_load_error}")


class ZipfGen:
    """Zipf(theta) ranks over [0, n); theta <= 0 means uniform."""

    def __init__(self, n: int, theta: float = 0.99, seed: int = 0):
        _require()
        self._h = _shn_zipf_new(n, float(theta), seed)
        if not self._h:
            raise MemoryError("zipf_new failed")

    def sample(self, size: int) -> np.ndarray:
        out = np.empty(size, np.uint64)
        _shn_zipf_fill(self._h, _u64p(out), size)
        return out

    def __del__(self):
        h, f = getattr(self, "_h", None), globals().get("_shn_zipf_free")
        if h and f:
            f(h)
            self._h = None


class LatencyHistogram:
    """Thread-safe 0.1 µs-bucket histogram; percentiles in µs."""

    def __init__(self):
        _require()
        self._h = _shn_hist_new()
        if not self._h:
            raise MemoryError("hist_new failed")

    def record_ns(self, ns: int) -> None:
        _shn_hist_record(self._h, int(ns))

    def record_many_ns(self, ns: np.ndarray) -> None:
        ns = np.ascontiguousarray(ns, np.uint64)
        _shn_hist_record_many(self._h, _u64p(ns), ns.size)

    def record_batch(self, span_ns: int, count: int) -> None:
        """count ops that completed together after span_ns (one step)."""
        _shn_hist_record_batch(self._h, int(span_ns), int(count))

    @property
    def count(self) -> int:
        return int(_shn_hist_count(self._h))

    def percentiles_us(self, qs=(0.5, 0.9, 0.95, 0.99, 0.999)) -> dict:
        q = np.asarray(qs, np.float64)
        out = np.empty(q.size, np.float64)
        _shn_hist_percentiles(self._h, q.ctypes.data_as(
            ct.POINTER(ct.c_double)), q.size,
            out.ctypes.data_as(ct.POINTER(ct.c_double)))
        return {"p" + ("%g" % (v * 100)).replace(".", ""): float(o)
                for v, o in zip(qs, out)}

    def reset(self) -> None:
        _shn_hist_reset(self._h)

    def __del__(self):
        h, f = getattr(self, "_h", None), globals().get("_shn_hist_free")
        if h and f:
            f(h)
            self._h = None


class SkipList:
    """Concurrent (key: u64 -> value: u64) skiplist; seek_ge iteration."""

    def __init__(self, capacity: int):
        _require()
        self._h = _shn_skl_new(capacity)
        if not self._h:
            raise MemoryError(f"skiplist alloc failed (capacity={capacity})")

    def insert(self, key: int, value: int) -> int:
        r = _shn_skl_insert(self._h, key, value)
        if r < 0:
            raise MemoryError("skiplist arena full")
        return r

    def seek_ge(self, key: int):
        k, v = ct.c_uint64(), ct.c_uint64()
        if _shn_skl_seek_ge(self._h, key, ct.byref(k), ct.byref(v)):
            return int(k.value), int(v.value)
        return None

    def __len__(self) -> int:
        return int(_shn_skl_count(self._h))

    def __del__(self):
        h, f = getattr(self, "_h", None), globals().get("_shn_skl_free")
        if h and f:
            f(h)
            self._h = None


STAT_FIELDS = ("hits", "misses", "adds", "evictions", "invalidates",
               "used_slots", "capacity", "skiplist_nodes", "add_fails")


class IndexCache:
    """Range -> leaf-address cache (IndexCache.h role); see src docs."""

    def __init__(self, capacity: int = 1 << 16):
        _require()
        self._h = _shn_cache_new(capacity)
        if not self._h:
            raise MemoryError(
                f"index cache alloc failed (capacity={capacity}; "
                "max 2**28 entries)")

    def add(self, from_key: int, to_key: int, ptr: int) -> int:
        return _shn_cache_add(self._h, from_key, to_key, ptr)

    def add_many(self, from_keys, to_keys, ptrs) -> None:
        f = np.ascontiguousarray(from_keys, np.uint64)
        t = np.ascontiguousarray(to_keys, np.uint64)
        p = np.ascontiguousarray(ptrs, np.uint64)
        assert f.size == t.size == p.size
        _shn_cache_add_many(self._h, _u64p(f), _u64p(t), _u64p(p), f.size)

    def lookup(self, key: int) -> int:
        """-> leaf addr, or 0 on miss."""
        return int(_shn_cache_lookup(self._h, key))

    def lookup_many(self, keys) -> np.ndarray:
        k = np.ascontiguousarray(keys, np.uint64)
        out = np.empty(k.size, np.uint64)
        _shn_cache_lookup_many(self._h, _u64p(k), k.size, _u64p(out))
        return out

    def invalidate(self, key: int) -> bool:
        return bool(_shn_cache_invalidate(self._h, key))

    def stats(self) -> dict:
        out = np.zeros(9, np.uint64)
        _shn_cache_stats(self._h, _u64p(out))
        return dict(zip(STAT_FIELDS, (int(x) for x in out)))

    def hit_rate(self) -> float:
        s = self.stats()
        tot = s["hits"] + s["misses"]
        return s["hits"] / tot if tot else 0.0

    def __del__(self):
        h, f = getattr(self, "_h", None), globals().get("_shn_cache_free")
        if h and f:
            f(h)
            self._h = None


class PrepBuffers:
    """One reusable output buffer set for :class:`BatchPrep` — hold two and
    alternate to double-buffer host prep against device steps."""

    __slots__ = ("khi", "klo", "start", "active", "inv", "keys", "n_uniq")

    def __init__(self, batch: int, capacity: int, with_keys: bool = False):
        self.khi = np.empty(capacity, np.int32)
        self.klo = np.empty(capacity, np.int32)
        self.start = np.empty(capacity, np.int32)
        self.active = np.empty(capacity, np.uint8)
        self.inv = np.empty(batch, np.int32)
        self.keys = np.empty(batch, np.uint64) if with_keys else None
        self.n_uniq = 0


class BatchPrep:
    """Fused single-pass batch prep: zipf sample -> keyspace gather ->
    unique+inverse (epoch-tagged hash table) -> router-table probe.

    The native replacement for the numpy prep pipeline (sort-based
    ``np.unique`` + separate router gather); see ``src/prep.cc``.  The
    reference's clients do this work inline in the open benchmark loop
    (``test/benchmark.cpp:159-188``); this class makes the batched engine's
    equivalent cheap enough to sit inside the timed serving loop.

    ``capacity`` bounds the unique keys per batch (the padded device batch
    width); ``run_*`` raises :class:`PrepOverflow` when a batch exceeds it
    so the caller can re-plan with a wider buffer set.
    """

    def __init__(self, batch: int, capacity: int, n_keys: int = 0,
                 theta: float = 0.0, seed: int = 0, salt: int = 0):
        """``salt`` != 0 enables the synthetic rank->key mode: the client
        key for zipf rank r is ``mix64(r ^ salt)`` computed arithmetically
        (build the matching tree keyspace with :func:`synthetic_keyspace`),
        so no keyspace gather sits in the serving loop — the reference
        benchmark's own convention (its key IS the zipf rank)."""
        _require()
        self.batch, self.capacity = int(batch), int(capacity)
        self._h = _shn_prep_new(int(n_keys), float(theta), int(seed),
                                int(batch), int(capacity), int(salt))
        if not self._h:
            raise MemoryError("prep_new failed")

    def buffers(self, with_keys: bool = False) -> PrepBuffers:
        return PrepBuffers(self.batch, self.capacity, with_keys)

    @staticmethod
    def _table_args(table: np.ndarray | None, shift: int, default_start: int):
        if table is None:
            return None, 0, 0, np.int32(default_start)
        t = np.ascontiguousarray(table, np.int32)
        return (t.ctypes.data_as(ct.POINTER(ct.c_int32)), t.size,
                int(shift), np.int32(default_start))

    def _finish(self, n: int, buf: PrepBuffers) -> PrepBuffers:
        if n == -1:
            raise PrepOverflow(
                f"batch exceeded unique capacity {self.capacity}")
        if n < 0:
            raise ConfigError("bad prep arguments")
        buf.n_uniq = int(n)
        return buf

    def run_keys(self, keys: np.ndarray, buf: PrepBuffers,
                 table: np.ndarray | None, shift: int = 0,
                 default_start: int = 0) -> PrepBuffers:
        """Dedup + probe an explicit key batch (<= batch keys)."""
        k = np.ascontiguousarray(keys, np.uint64)
        tp, nb, sh, ds = self._table_args(table, shift, default_start)
        i32 = ct.POINTER(ct.c_int32)
        n = _shn_prep_run_keys(
            self._h, _u64p(k), k.size, tp, nb, sh, ds,
            buf.khi.ctypes.data_as(i32), buf.klo.ctypes.data_as(i32),
            buf.start.ctypes.data_as(i32),
            buf.active.ctypes.data_as(ct.POINTER(ct.c_uint8)),
            buf.inv.ctypes.data_as(i32))
        return self._finish(n, buf)

    def run_zipf(self, keyspace: np.ndarray | None, buf: PrepBuffers,
                 table: np.ndarray | None, shift: int = 0,
                 default_start: int = 0,
                 want_keys: bool = False) -> PrepBuffers:
        """Sample `batch` zipf ops over ``keyspace`` (or the synthetic map
        when constructed with a salt — pass ``keyspace=None``) and prep
        them; with ``want_keys`` the raw client keys land in ``buf.keys``
        (skipped by default: the extra batch*8-byte memcpy is pure waste
        in a timed serving loop)."""
        ksp = None
        if keyspace is not None:
            ks = np.ascontiguousarray(keyspace, np.uint64)
            ksp = _u64p(ks)
        tp, nb, sh, ds = self._table_args(table, shift, default_start)
        i32 = ct.POINTER(ct.c_int32)
        okp = (_u64p(buf.keys) if want_keys and buf.keys is not None
               else None)
        n = _shn_prep_run_zipf(
            self._h, ksp, okp, tp, nb, sh, ds,
            buf.khi.ctypes.data_as(i32), buf.klo.ctypes.data_as(i32),
            buf.start.ctypes.data_as(i32),
            buf.active.ctypes.data_as(ct.POINTER(ct.c_uint8)),
            buf.inv.ctypes.data_as(i32))
        return self._finish(n, buf)

    def __del__(self):
        h, f = getattr(self, "_h", None), globals().get("_shn_prep_free")
        if h and f:
            f(h)
            self._h = None


class PrepOverflow(ShermanError, RuntimeError):
    """A batch's unique-key count exceeded the planned device width."""


def mix64(x) -> np.ndarray:
    """Vectorized splitmix64 finalizer — bit-exact with prep.cc's mix64
    (canonical host implementation lives in ops.bits.mix64_np; this is
    an alias so the two can never drift)."""
    from sherman_tpu.ops.bits import mix64_np
    return mix64_np(x)


def synthetic_keyspace(n_keys: int, salt: int):
    """The sorted tree keyspace matching BatchPrep's synthetic mode: rank
    r's client key is ``mix64(r ^ salt)``.  Returns (sorted_keys,
    rank_to_key) where rank_to_key[r] is rank r's key.  mix64 is a
    bijection, so distinct ranks never collide; the only failure mode is
    an out-of-range key (0 or KEY_POS_INF), which is CERTAIN for key 0
    when ``salt < n_keys`` (rank == salt maps to mix64(0) == 0) — pick a
    salt with bits above the rank range and the retry loop is one-shot."""
    from sherman_tpu import config as C
    rank_to_key = mix64(np.arange(n_keys, dtype=np.uint64)
                        ^ np.uint64(salt))
    keys = np.sort(rank_to_key)
    if (np.diff(keys) == 0).any() or keys[0] < C.KEY_MIN \
            or keys[-1] > C.KEY_MAX:
        raise ConfigError(f"salt {salt} collides; pick another")
    return keys, rank_to_key


class WRLock:
    """Spinning writer-preference RW lock (``include/WRLock.h`` parity:
    the reference guards the DSM singleton + the IndexCache delay-free
    list with it)."""

    def __init__(self):
        _require()
        self._h = _shn_rw_new()
        if not self._h:
            raise MemoryError("rw lock alloc failed")

    def rlock(self) -> None:
        _shn_rw_rlock(self._h)

    def runlock(self) -> None:
        _shn_rw_runlock(self._h)

    def wlock(self) -> None:
        _shn_rw_wlock(self._h)

    def wunlock(self) -> None:
        _shn_rw_wunlock(self._h)

    def __del__(self):
        h, f = getattr(self, "_h", None), globals().get("_shn_rw_free")
        if h and f:
            f(h)
            self._h = None


class LocalLockTable:
    """Node-local ticket locks with bounded global-lock hand-over."""

    def __init__(self, n_locks: int):
        _require()
        self.n = n_locks
        self._h = _shn_lt_new(n_locks)
        if not self._h:
            raise MemoryError(f"lock table alloc failed (n={n_locks})")

    def acquire(self, i: int) -> bool:
        """Blocks. -> True if the GLOBAL lock was handed over too."""
        return bool(_shn_lt_acquire(self._h, i))

    def can_handover(self, i: int) -> bool:
        """Holder-only probe: would release(True) hand over right now?
        True is binding-safe (waiters block); after a False probe the
        holder must release(False) — see locks.cc."""
        return bool(_shn_lt_can_handover(self._h, i))

    def release(self, i: int, handover_ok: bool = True) -> bool:
        """-> True if handed over (do NOT release the global lock)."""
        return bool(_shn_lt_release(self._h, i, int(handover_ok)))

    def __del__(self):
        h, f = getattr(self, "_h", None), globals().get("_shn_lt_free")
        if h and f:
            f(h)
            self._h = None
