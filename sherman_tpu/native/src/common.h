// Shared helpers for the sherman_tpu native runtime library.
//
// The reference system is 100% native C++ (SURVEY.md §2); these sources are
// the TPU build's native runtime ring: everything host-side that sits on the
// operation hot path but outside the XLA-compiled data plane.  Exposed to
// Python through a plain C ABI (ctypes), no pybind11.
#pragma once

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>

#if defined(__GNUC__)
#define SHN_EXPORT extern "C" __attribute__((visibility("default")))
#else
#define SHN_EXPORT extern "C"
#endif

namespace shn {

// xorshift128+ — fast per-object PRNG (workload gen, eviction sampling).
struct Rng {
  uint64_t s0, s1;
  explicit Rng(uint64_t seed) {
    // splitmix64 seeding
    uint64_t z = seed + 0x9e3779b97f4a7c15ull;
    auto mix = [&z]() {
      z += 0x9e3779b97f4a7c15ull;
      uint64_t x = z;
      x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
      x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
      return x ^ (x >> 31);
    };
    s0 = mix();
    s1 = mix();
    if (s0 == 0 && s1 == 0) s0 = 1;
  }
  inline uint64_t next() {
    uint64_t x = s0, y = s1;
    s0 = y;
    x ^= x << 23;
    s1 = x ^ y ^ (x >> 17) ^ (y >> 26);
    return s1 + y;
  }
  inline double next_double() {  // [0, 1)
    return (next() >> 11) * (1.0 / 9007199254740992.0);
  }
};

}  // namespace shn
