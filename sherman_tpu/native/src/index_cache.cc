// IndexCache — the compute-node range -> leaf-address cache, native.
//
// Role parity: the reference's IndexCache (include/IndexCache.h) +
// CacheEntry (include/CacheEntry.h): a concurrent skiplist of key-range
// entries that lets a cache hit skip every internal tree level
// (Tree.cpp:415-427), with CAS invalidation, an epoch-style delay-free
// list (~30 µs, IndexCache.h:137-149), 2-random-choice eviction by
// frequency (IndexCache.h:227-259), and hit/miss statistics.
//
// TPU-first difference: the reference caches whole 1 KB level-1 page
// *contents* and re-scans them per lookup; here an entry maps a child
// range [from, to) directly to the child (leaf) address — same remote-read
// savings (internal levels skipped, one leaf read per hit), no page scan,
// and the same entry granularity the device-side LeafRouter consumes, so
// the host cache can seed the router table.
//
// Concurrency: arena slots are recycled (delay-free ring), so each entry
// carries a seqlock version — writers bump it odd around a slot rewrite,
// readers snapshot it before/after and treat any movement as a miss (the
// caller just descends normally; a spurious miss never breaks anything).
#include <chrono>
#include <new>

#include "skiplist.h"

namespace {

using shn::kNil;

struct Entry {
  std::atomic<uint32_t> ver{0};   // seqlock: odd = being rewritten
  std::atomic<uint32_t> freq{0};  // lookup popularity (eviction signal)
  std::atomic<uint32_t> live{0};  // 1 while the slot's [from,to) is current
  std::atomic<uint64_t> from{0};  // inclusive
  std::atomic<uint64_t> to{0};    // exclusive
  std::atomic<uint64_t> ptr{0};   // leaf address (0 = invalidated)
};

struct FreeSlot {
  uint32_t idx;
  uint64_t t_ns;  // when it was invalidated (delay-free epoch)
};

inline uint64_t now_ns() {
  return (uint64_t)std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

constexpr uint64_t kDelayFreeNs = 30'000;  // ~30 µs, IndexCache.h:137-149

struct IndexCache {
  uint32_t capacity;
  shn::SkipList index;  // key = entry.to, value = arena slot
  Entry* arena;
  std::atomic<uint32_t> used{0};
  // delay-free ring, guarded by a tiny spinlock (reuse/eviction is rare
  // and off the hot lookup path)
  FreeSlot* free_ring;
  uint32_t free_cap;
  std::atomic<uint32_t> free_head{0}, free_tail{0};
  std::atomic<uint32_t> free_lock{0};
  // stats
  std::atomic<uint64_t> hits{0}, misses{0}, adds{0}, evictions{0},
      invalidates{0}, add_fails{0};

  explicit IndexCache(uint32_t cap)
      // skiplist sized 4x: arena slots are reused but skiplist nodes are
      // append-only (lost-CAS nodes + re-added ranges consume fresh nodes);
      // the factory bounds cap so the multiply cannot wrap
      : capacity(cap), index(cap * 4) {
    arena = new (std::nothrow) Entry[cap];
    free_cap = cap + 1;
    free_ring = (FreeSlot*)std::calloc(free_cap, sizeof(FreeSlot));
  }
  ~IndexCache() {
    delete[] arena;
    std::free(free_ring);
  }
  bool ok() const { return arena && free_ring && index.ok(); }

  void spin_lock() {
    uint32_t e = 0;
    while (!free_lock.compare_exchange_weak(e, 1u,
                                            std::memory_order_acquire))
      e = 0;
  }
  void spin_unlock() { free_lock.store(0, std::memory_order_release); }

  void push_free(uint32_t idx) {
    spin_lock();
    uint32_t t = free_tail.load(std::memory_order_relaxed);
    uint32_t nt = (t + 1) % free_cap;
    if (nt != free_head.load(std::memory_order_relaxed)) {
      free_ring[t] = {idx, now_ns()};
      free_tail.store(nt, std::memory_order_relaxed);
    }
    spin_unlock();
  }

  // Pop a slot whose delay-free epoch has passed; kNil if none ready.
  uint32_t pop_free() {
    spin_lock();
    uint32_t h = free_head.load(std::memory_order_relaxed);
    uint32_t idx = kNil;
    if (h != free_tail.load(std::memory_order_relaxed) &&
        now_ns() - free_ring[h].t_ns >= kDelayFreeNs) {
      idx = free_ring[h].idx;
      free_head.store((h + 1) % free_cap, std::memory_order_relaxed);
    }
    spin_unlock();
    return idx;
  }

  // 2-random-choice: invalidate the less-popular of two sampled live slots
  // and queue it for delayed reuse (IndexCache.h:227-259 semantics).
  void evict_one() {
    static thread_local shn::Rng rng{0xe71c ^ (uint64_t)(uintptr_t)&rng};
    uint32_t n = used.load(std::memory_order_relaxed);
    if (n == 0) return;
    if (n > capacity) n = capacity;
    for (int attempt = 0; attempt < 16; ++attempt) {
      uint32_t a = (uint32_t)(rng.next() % n);
      uint32_t b = (uint32_t)(rng.next() % n);
      uint32_t victim =
          arena[a].freq.load(std::memory_order_relaxed) <=
                  arena[b].freq.load(std::memory_order_relaxed)
              ? a
              : b;
      uint32_t one = 1;
      if (arena[victim].live.compare_exchange_strong(
              one, 0u, std::memory_order_acq_rel)) {
        arena[victim].ptr.store(0, std::memory_order_release);
        push_free(victim);
        evictions.fetch_add(1, std::memory_order_relaxed);
        return;
      }
    }
  }

  uint32_t alloc_slot() {
    uint32_t i = used.load(std::memory_order_relaxed);
    while (i < capacity) {
      if (used.compare_exchange_weak(i, i + 1,
                                     std::memory_order_acq_rel))
        return i;
    }
    uint32_t f = pop_free();
    if (f != kNil) return f;
    evict_one();
    f = pop_free();  // may still be in its delay window
    return f;        // kNil -> caller drops the add (cache full)
  }

  // Insert or refresh [from, to) -> ptr.  >= 0 ok, < 0 dropped.
  int add(uint64_t from, uint64_t to, uint64_t ptr) {
    if (to <= from || ptr == 0) return -2;
    adds.fetch_add(1, std::memory_order_relaxed);
    // fast path: same range already present -> refresh its ptr
    uint32_t n = index.seek_ge(to);
    if (n != kNil && index.arena[n].key == to) {
      uint32_t slot =
          (uint32_t)index.arena[n].value.load(std::memory_order_acquire);
      if (slot < capacity &&
          arena[slot].live.load(std::memory_order_acquire) &&
          arena[slot].from.load(std::memory_order_relaxed) == from &&
          arena[slot].to.load(std::memory_order_relaxed) == to) {
        arena[slot].ptr.store(ptr, std::memory_order_release);
        return 1;
      }
    }
    uint32_t slot = alloc_slot();
    if (slot == kNil) {
      add_fails.fetch_add(1, std::memory_order_relaxed);
      return -1;
    }
    // seqlock write: odd while the slot's identity is in flux
    arena[slot].ver.fetch_add(1, std::memory_order_acq_rel);
    arena[slot].from.store(from, std::memory_order_relaxed);
    arena[slot].to.store(to, std::memory_order_relaxed);
    arena[slot].freq.store(1, std::memory_order_relaxed);
    arena[slot].ptr.store(ptr, std::memory_order_relaxed);
    arena[slot].live.store(1, std::memory_order_relaxed);
    arena[slot].ver.fetch_add(1, std::memory_order_release);
    if (index.insert(to, slot) < 0) {
      // skiplist node arena exhausted: roll the slot back so it is not a
      // live-but-unreachable leak, and report the drop to the caller
      uint32_t one = 1;
      if (arena[slot].live.compare_exchange_strong(
              one, 0u, std::memory_order_acq_rel)) {
        arena[slot].ptr.store(0, std::memory_order_release);
        push_free(slot);
      }
      add_fails.fetch_add(1, std::memory_order_relaxed);
      return -1;
    }
    return 0;
  }

  // -> leaf ptr or 0.  Bumps freq + hit/miss counters.
  uint64_t lookup(uint64_t key) {
    // entry covers key iff from <= key < to; index key is `to`, so the
    // candidate is the first node with to > key i.e. seek_ge(key + 1)
    uint32_t n = index.seek_ge(key + 1);
    if (n != kNil) {
      uint32_t slot =
          (uint32_t)index.arena[n].value.load(std::memory_order_acquire);
      if (slot < capacity) {
        Entry& e = arena[slot];
        uint32_t v1 = e.ver.load(std::memory_order_acquire);
        if (!(v1 & 1) && e.live.load(std::memory_order_acquire) &&
            e.to.load(std::memory_order_relaxed) == index.arena[n].key &&
            e.from.load(std::memory_order_relaxed) <= key &&
            key < e.to.load(std::memory_order_relaxed)) {
          uint64_t p = e.ptr.load(std::memory_order_acquire);
          if (p != 0 &&
              e.ver.load(std::memory_order_acquire) == v1) {
            e.freq.fetch_add(1, std::memory_order_relaxed);
            hits.fetch_add(1, std::memory_order_relaxed);
            return p;
          }
        }
      }
    }
    misses.fetch_add(1, std::memory_order_relaxed);
    return 0;
  }

  // CAS-null the entry covering key (stale hit detected: IndexCache.h:209).
  int invalidate(uint64_t key) {
    uint32_t n = index.seek_ge(key + 1);
    if (n == kNil) return 0;
    uint32_t slot =
        (uint32_t)index.arena[n].value.load(std::memory_order_acquire);
    if (slot >= capacity ||
        arena[slot].from.load(std::memory_order_relaxed) > key ||
        key >= arena[slot].to.load(std::memory_order_relaxed))
      return 0;
    uint32_t one = 1;
    if (arena[slot].live.compare_exchange_strong(one, 0u,
                                                 std::memory_order_acq_rel)) {
      arena[slot].ptr.store(0, std::memory_order_release);
      push_free(slot);
      invalidates.fetch_add(1, std::memory_order_relaxed);
      return 1;
    }
    return 0;
  }
};

}  // namespace

SHN_EXPORT void* shn_cache_new(uint64_t capacity) {
  // bound so cap*4 (skiplist) and cap+1 (free ring) fit in uint32
  if (capacity == 0 || capacity > (1ull << 28)) return nullptr;
  auto* c = new (std::nothrow) IndexCache((uint32_t)capacity);
  if (c && !c->ok()) {
    delete c;
    return nullptr;
  }
  return c;
}

SHN_EXPORT void shn_cache_free(void* h) { delete (IndexCache*)h; }

SHN_EXPORT int shn_cache_add(void* h, uint64_t from, uint64_t to,
                             uint64_t ptr) {
  return ((IndexCache*)h)->add(from, to, ptr);
}

SHN_EXPORT void shn_cache_add_many(void* h, const uint64_t* from,
                                   const uint64_t* to, const uint64_t* ptr,
                                   uint64_t n) {
  auto* c = (IndexCache*)h;
  for (uint64_t i = 0; i < n; ++i) c->add(from[i], to[i], ptr[i]);
}

SHN_EXPORT uint64_t shn_cache_lookup(void* h, uint64_t key) {
  return ((IndexCache*)h)->lookup(key);
}

SHN_EXPORT void shn_cache_lookup_many(void* h, const uint64_t* keys,
                                      uint64_t n, uint64_t* out_ptrs) {
  auto* c = (IndexCache*)h;
  for (uint64_t i = 0; i < n; ++i) out_ptrs[i] = c->lookup(keys[i]);
}

SHN_EXPORT int shn_cache_invalidate(void* h, uint64_t key) {
  return ((IndexCache*)h)->invalidate(key);
}

// out[9] = hits, misses, adds, evictions, invalidates, used_slots,
//          capacity, skiplist_nodes, add_fails
SHN_EXPORT void shn_cache_stats(void* h, uint64_t* out) {
  auto* c = (IndexCache*)h;
  out[0] = c->hits.load(std::memory_order_relaxed);
  out[1] = c->misses.load(std::memory_order_relaxed);
  out[2] = c->adds.load(std::memory_order_relaxed);
  out[3] = c->evictions.load(std::memory_order_relaxed);
  out[4] = c->invalidates.load(std::memory_order_relaxed);
  uint32_t u = c->used.load(std::memory_order_relaxed);
  out[5] = u < c->capacity ? u : c->capacity;
  out[6] = c->capacity;
  out[7] = c->index.used.load(std::memory_order_relaxed);
  out[8] = c->add_fails.load(std::memory_order_relaxed);
}
