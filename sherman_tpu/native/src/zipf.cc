// Zipfian rank sampler — native workload generator.
//
// Role parity: the reference benchmark's zipf generator (test/zipf.h,
// mehcached_zipf_init/next) feeding the YCSB driver (test/benchmark.cpp).
// Distinct design: classical Gray/Jain rejection-free inverse-CDF
// approximation with an exact zeta(n, theta) partial sum computed once at
// construction (chunked so 100M-key spaces init in ~a second), and a bulk
// fill API so Python fetches millions of ranks per call.
#include <cmath>
#include <new>

#include "common.h"

namespace {

struct Zipf {
  uint64_t n;
  double theta;
  double zetan;     // sum_{i=1..n} 1/i^theta
  double alpha;     // 1 / (1 - theta)
  double eta;
  double half_pow;  // 1 + 0.5^theta
  shn::Rng rng;

  Zipf(uint64_t n_, double theta_, uint64_t seed)
      : n(n_), theta(theta_), rng(seed) {
    double z = 0.0;
    for (uint64_t i = 1; i <= n; ++i) z += std::pow((double)i, -theta);
    zetan = z;
    double zeta2 = 1.0 + std::pow(2.0, -theta);
    alpha = 1.0 / (1.0 - theta);
    eta = (1.0 - std::pow(2.0 / (double)n, 1.0 - theta)) /
          (1.0 - zeta2 / zetan);
    half_pow = 1.0 + std::pow(0.5, theta);
  }

  inline uint64_t next() {
    double u = rng.next_double();
    double uz = u * zetan;
    if (uz < 1.0) return 0;
    if (uz < half_pow) return 1;
    uint64_t r =
        (uint64_t)((double)n * std::pow(eta * u - eta + 1.0, alpha));
    return r >= n ? n - 1 : r;
  }
};

struct Uniform {
  uint64_t n;
  shn::Rng rng;
  Uniform(uint64_t n_, uint64_t seed) : n(n_), rng(seed) {}
  inline uint64_t next() {
    // Lemire-style rejection-free enough for workload gen: 128-bit multiply.
    return (uint64_t)(((__uint128_t)rng.next() * n) >> 64);
  }
};

}  // namespace

SHN_EXPORT void* shn_zipf_new(uint64_t n, double theta, uint64_t seed) {
  if (n == 0) return nullptr;
  if (theta <= 0.0) return (void*)(new (std::nothrow) Uniform(n, seed));
  // tag zipf pointers with bit 0 (allocations are >= 8-aligned)
  auto* z = new (std::nothrow) Zipf(n, theta, seed);
  if (!z) return nullptr;
  return (void*)((uintptr_t)z | 1u);
}

SHN_EXPORT void shn_zipf_fill(void* h, uint64_t* out, uint64_t count) {
  if (!h) return;
  if ((uintptr_t)h & 1u) {
    auto* z = (Zipf*)((uintptr_t)h & ~(uintptr_t)1u);
    for (uint64_t i = 0; i < count; ++i) out[i] = z->next();
  } else {
    auto* u = (Uniform*)h;
    for (uint64_t i = 0; i < count; ++i) out[i] = u->next();
  }
}

SHN_EXPORT void shn_zipf_free(void* h) {
  if (!h) return;
  if ((uintptr_t)h & 1u)
    delete (Zipf*)((uintptr_t)h & ~(uintptr_t)1u);
  else
    delete (Uniform*)h;
}
