// Zipfian rank sampler — native workload generator (C ABI over zipf.h).
//
// The samplers themselves live in zipf.h so the fused batch-prep pipeline
// (prep.cc) inlines them into its streaming loop.
#include <new>

#include "zipf.h"

using shn::UniformGen;
using shn::Zipf;

namespace {
using Uniform = UniformGen;
}  // namespace

SHN_EXPORT void* shn_zipf_new(uint64_t n, double theta, uint64_t seed) {
  if (n == 0) return nullptr;
  if (theta <= 0.0) return (void*)(new (std::nothrow) Uniform(n, seed));
  // tag zipf pointers with bit 0 (allocations are >= 8-aligned)
  auto* z = new (std::nothrow) Zipf(n, theta, seed);
  if (!z) return nullptr;
  return (void*)((uintptr_t)z | 1u);
}

SHN_EXPORT void shn_zipf_fill(void* h, uint64_t* out, uint64_t count) {
  if (!h) return;
  if ((uintptr_t)h & 1u) {
    auto* z = (Zipf*)((uintptr_t)h & ~(uintptr_t)1u);
    for (uint64_t i = 0; i < count; ++i) out[i] = z->next();
  } else {
    auto* u = (Uniform*)h;
    for (uint64_t i = 0; i < count; ++i) out[i] = u->next();
  }
}

SHN_EXPORT void shn_zipf_free(void* h) {
  if (!h) return;
  if ((uintptr_t)h & 1u)
    delete (Zipf*)((uintptr_t)h & ~(uintptr_t)1u);
  else
    delete (Uniform*)h;
}
