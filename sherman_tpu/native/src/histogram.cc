// Latency histogram — fixed 0.1 µs buckets, thread-safe, percentile reduce.
//
// Role parity: the reference's per-thread latency windows
// (latency[MAX_APP_THREAD][LATENCY_WINDOWS], src/Tree.cpp:17) reduced to
// p50..p999 by cal_latency (test/benchmark.cpp:207-249).  Design here:
// one shared atomic bucket array (records are a single relaxed fetch-add,
// so many Python / native threads can record concurrently), percentiles by
// a single pass over the cumulative sum.
#include <new>

#include "common.h"

namespace {

constexpr uint64_t kBucketNs = 100;     // 0.1 µs per bucket
constexpr uint64_t kBuckets = 1 << 20;  // covers up to ~105 ms

struct Hist {
  std::atomic<uint64_t> total{0};
  std::atomic<uint64_t> buckets[kBuckets];
  Hist() {
    for (uint64_t i = 0; i < kBuckets; ++i)
      buckets[i].store(0, std::memory_order_relaxed);
  }
};

}  // namespace

SHN_EXPORT void* shn_hist_new() { return new (std::nothrow) Hist(); }

SHN_EXPORT void shn_hist_free(void* h) { delete (Hist*)h; }

SHN_EXPORT void shn_hist_reset(void* h) {
  auto* hist = (Hist*)h;
  hist->total.store(0, std::memory_order_relaxed);
  for (uint64_t i = 0; i < kBuckets; ++i)
    hist->buckets[i].store(0, std::memory_order_relaxed);
}

static inline void record_one(Hist* hist, uint64_t ns) {
  uint64_t b = ns / kBucketNs;
  if (b >= kBuckets) b = kBuckets - 1;
  hist->buckets[b].fetch_add(1, std::memory_order_relaxed);
  hist->total.fetch_add(1, std::memory_order_relaxed);
}

SHN_EXPORT void shn_hist_record(void* h, uint64_t ns) {
  record_one((Hist*)h, ns);
}

SHN_EXPORT void shn_hist_record_many(void* h, const uint64_t* ns,
                                     uint64_t count) {
  auto* hist = (Hist*)h;
  for (uint64_t i = 0; i < count; ++i) record_one(hist, ns[i]);
}

// Record `count` ops that together took `span_ns` (a batched step): each op's
// latency is the span (they completed together), weight = count.
SHN_EXPORT void shn_hist_record_batch(void* h, uint64_t span_ns,
                                      uint64_t count) {
  auto* hist = (Hist*)h;
  uint64_t b = span_ns / kBucketNs;
  if (b >= kBuckets) b = kBuckets - 1;
  hist->buckets[b].fetch_add(count, std::memory_order_relaxed);
  hist->total.fetch_add(count, std::memory_order_relaxed);
}

SHN_EXPORT uint64_t shn_hist_count(void* h) {
  return ((Hist*)h)->total.load(std::memory_order_relaxed);
}

// qs in (0,1], ascending; out_us[i] = bucket midpoint latency in µs.
SHN_EXPORT void shn_hist_percentiles(void* h, const double* qs, uint64_t nq,
                                     double* out_us) {
  auto* hist = (Hist*)h;
  uint64_t total = hist->total.load(std::memory_order_relaxed);
  if (total == 0) {
    for (uint64_t i = 0; i < nq; ++i) out_us[i] = 0.0;
    return;
  }
  uint64_t cum = 0, qi = 0;
  for (uint64_t b = 0; b < kBuckets && qi < nq; ++b) {
    cum += hist->buckets[b].load(std::memory_order_relaxed);
    while (qi < nq && (double)cum >= qs[qi] * (double)total) {
      out_us[qi] = ((double)b + 0.5) * (double)kBucketNs / 1000.0;
      ++qi;
    }
  }
  while (qi < nq) out_us[qi++] = (double)(kBuckets * kBucketNs) / 1000.0;
}
