// C ABI for the concurrent skiplist (skiplist.h) — standalone use from
// Python (the reference's skiplist_test is the one host-only unit test,
// test/skiplist_test.cpp; tests/test_native.py mirrors it).
#include <new>

#include "skiplist.h"

using shn::SkipList;

SHN_EXPORT void* shn_skl_new(uint64_t capacity) {
  if (capacity == 0 || capacity > 0xFFFFFFF0ull) return nullptr;
  auto* sl = new (std::nothrow) SkipList((uint32_t)capacity);
  if (sl && !sl->ok()) {
    delete sl;
    return nullptr;
  }
  return sl;
}

SHN_EXPORT void shn_skl_free(void* h) { delete (SkipList*)h; }

SHN_EXPORT int shn_skl_insert(void* h, uint64_t key, uint64_t value) {
  return ((SkipList*)h)->insert(key, value);
}

// -> 1 found (first entry with key >= target), 0 none.
SHN_EXPORT int shn_skl_seek_ge(void* h, uint64_t key, uint64_t* out_key,
                               uint64_t* out_value) {
  auto* sl = (SkipList*)h;
  uint32_t n = sl->seek_ge(key);
  if (n == shn::kNil) return 0;
  *out_key = sl->arena[n].key;
  *out_value = sl->arena[n].value.load(std::memory_order_acquire);
  return 1;
}

SHN_EXPORT uint64_t shn_skl_count(void* h) {
  auto* sl = (SkipList*)h;
  uint64_t c = 0;
  uint32_t x = sl->arena[sl->head].next[0].load(std::memory_order_acquire);
  while (x != shn::kNil) {
    ++c;
    x = sl->arena[x].next[0].load(std::memory_order_acquire);
  }
  return c;
}
