// Concurrent skiplist — lock-free insert, wait-free seek, arena-backed.
// (See skiplist.cc for the C ABI; index_cache.cc embeds the structure.)
//
// Role parity: the reference's third_party/inlineskiplist.h.  Original
// design: fixed node arena addressed by 32-bit indices (cheap atomics, no
// ABA — nodes are never freed), towers inline, links CAS-published bottom-up.
#pragma once

#include "common.h"

namespace shn {

constexpr int kMaxHeight = 16;
constexpr uint32_t kNil = 0xFFFFFFFFu;

struct SklNode {
  uint64_t key;
  std::atomic<uint64_t> value;
  int32_t height;
  std::atomic<uint32_t> next[kMaxHeight];
};

struct SkipList {
  uint32_t capacity;
  std::atomic<uint32_t> used{0};
  std::atomic<int> max_height{1};
  SklNode* arena;
  uint32_t head;  // sentinel, acts as key = -inf

  explicit SkipList(uint32_t cap) : capacity(cap + 1) {
    arena = (SklNode*)std::calloc(capacity, sizeof(SklNode));
    if (!arena) {  // caller checks ok(); keep the object inert
      capacity = 0;
      head = kNil;
      return;
    }
    head = alloc_node(0, 0, kMaxHeight);
  }
  bool ok() const { return arena != nullptr; }
  ~SkipList() { std::free(arena); }
  SkipList(const SkipList&) = delete;

  uint32_t alloc_node(uint64_t key, uint64_t value, int height) {
    uint32_t i = used.fetch_add(1, std::memory_order_relaxed);
    if (i >= capacity) return kNil;
    SklNode& n = arena[i];
    n.key = key;
    n.value.store(value, std::memory_order_relaxed);
    n.height = height;
    for (int h = 0; h < height; ++h)
      n.next[h].store(kNil, std::memory_order_relaxed);
    return i;
  }

  int random_height() {
    // thread-local PRNG: insert() is concurrent, a shared generator would
    // race (and correlate tower heights across threads)
    static thread_local Rng rng{0x5eed ^ (uint64_t)(uintptr_t)&rng};
    int h = 1;
    while (h < kMaxHeight && (rng.next() & 3) == 0) ++h;  // p = 1/4
    return h;
  }

  // Greatest node with key < target at each level.  Fills ALL kMaxHeight
  // levels (not just the current max): a taller-than-max new node needs
  // valid preds above max_height, and the head tower spans full height.
  void find_preds(uint64_t target, uint32_t preds[kMaxHeight],
                  uint32_t succs[kMaxHeight]) {
    uint32_t x = head;
    for (int h = kMaxHeight - 1; h >= 0; --h) {
      while (true) {
        uint32_t nxt = arena[x].next[h].load(std::memory_order_acquire);
        if (nxt != kNil && arena[nxt].key < target)
          x = nxt;
        else {
          preds[h] = x;
          succs[h] = nxt;
          break;
        }
      }
    }
  }

  // Insert; overwrites value when key exists.  0 ok, -1 full, 1 updated.
  int insert(uint64_t key, uint64_t value) {
    uint32_t preds[kMaxHeight], succs[kMaxHeight];
    while (true) {
      find_preds(key, preds, succs);
      if (succs[0] != kNil && arena[succs[0]].key == key) {
        arena[succs[0]].value.store(value, std::memory_order_release);
        return 1;
      }
      int h = random_height();
      uint32_t node = alloc_node(key, value, h);
      if (node == kNil) return -1;
      int cur_max = max_height.load(std::memory_order_relaxed);
      while (h > cur_max &&
             !max_height.compare_exchange_weak(cur_max, h,
                                               std::memory_order_acq_rel)) {
      }
      // bottom level first: the node becomes visible atomically
      arena[node].next[0].store(succs[0], std::memory_order_relaxed);
      if (!arena[preds[0]].next[0].compare_exchange_strong(
              succs[0], node, std::memory_order_acq_rel))
        continue;  // bottom CAS lost: recompute (node index is wasted)
      for (int lvl = 1; lvl < h; ++lvl) {
        while (true) {
          arena[node].next[lvl].store(succs[lvl],
                                      std::memory_order_relaxed);
          if (arena[preds[lvl]].next[lvl].compare_exchange_strong(
                  succs[lvl], node, std::memory_order_acq_rel))
            break;
          find_preds(key, preds, succs);
        }
      }
      return 0;
    }
  }

  // First node with key >= target; kNil if none.
  uint32_t seek_ge(uint64_t target) {
    uint32_t preds[kMaxHeight], succs[kMaxHeight];
    find_preds(target, preds, succs);
    return succs[0];
  }
};

}  // namespace shn
