// Zipfian / uniform rank samplers — shared by the standalone generator
// (zipf.cc) and the fused batch-prep pipeline (prep.cc).
//
// Role parity: the reference benchmark's zipf generator (test/zipf.h,
// mehcached_zipf_init/next) feeding the YCSB driver (test/benchmark.cpp).
// Distinct design: classical Gray/Jain rejection-free inverse-CDF
// approximation with an exact zeta(n, theta) partial sum computed once at
// construction, and bulk APIs so callers amortize per-call overhead.
#pragma once

#include <cmath>

#include "common.h"

namespace shn {

// Fast x^a for x in (0, 1] via exp2(a * log2(x)) with polynomial
// approximations (atanh series for log2, 8-term Taylor for exp2).
// Relative rank error at theta=0.99 (a ~= 100) is ~1e-3 — a workload
// generator's inverse-CDF tolerance; the reference's own sampler uses an
// approximate pow the same way (test/zipf.h, MICA fast-pow role).
inline double fast_log2(double x) {
  uint64_t bits;
  memcpy(&bits, &x, 8);
  int e = (int)((bits >> 52) & 0x7ff) - 1023;
  bits = (bits & 0x000fffffffffffffull) | 0x3ff0000000000000ull;
  double m;
  memcpy(&m, &bits, 8);  // m in [1, 2)
  double t = (m - 1.0) / (m + 1.0);
  double t2 = t * t;
  // 2/ln2 * atanh-series through t^9
  double p = t * (2.885390081777927 +
                  t2 * (0.961796693925976 +
                        t2 * (0.577078016355585 +
                              t2 * (0.412198595302989 +
                                    t2 * 0.320598812316461))));
  return (double)e + p;
}

inline double fast_exp2(double y) {
  double fi = __builtin_floor(y);
  double f = y - fi;
  double z = f * 0.6931471805599453;  // f*ln2; e^z via Taylor to z^7
  double r = 1.0 +
             z * (1.0 +
                  z * (0.5 +
                       z * (1.0 / 6 +
                            z * (1.0 / 24 +
                                 z * (1.0 / 120 +
                                      z * (1.0 / 720 + z / 5040))))));
  uint64_t ebits = (uint64_t)(int64_t)((int)fi + 1023) << 52;
  double scale;
  memcpy(&scale, &ebits, 8);
  return r * scale;
}

struct Zipf {
  uint64_t n;
  double theta;
  double zetan;     // sum_{i=1..n} 1/i^theta
  double alpha;     // 1 / (1 - theta)
  double eta;
  double half_pow;  // 1 + 0.5^theta
  Rng rng;

  Zipf(uint64_t n_, double theta_, uint64_t seed)
      : n(n_), theta(theta_), rng(seed) {
    double z = 0.0;
    for (uint64_t i = 1; i <= n; ++i) z += std::pow((double)i, -theta);
    zetan = z;
    double zeta2 = 1.0 + std::pow(2.0, -theta);
    alpha = 1.0 / (1.0 - theta);
    eta = (1.0 - std::pow(2.0 / (double)n, 1.0 - theta)) /
          (1.0 - zeta2 / zetan);
    half_pow = 1.0 + std::pow(0.5, theta);
  }

  inline uint64_t next() {
    double u = rng.next_double();
    double uz = u * zetan;
    if (uz < 1.0) return 0;
    if (uz < half_pow) return 1;
    uint64_t r =
        (uint64_t)((double)n * std::pow(eta * u - eta + 1.0, alpha));
    return r >= n ? n - 1 : r;
  }

  // Hot-loop variant: fast_exp2/fast_log2 instead of std::pow (~4x).
  inline uint64_t next_fast() {
    double u = rng.next_double();
    double uz = u * zetan;
    if (uz < 1.0) return 0;
    if (uz < half_pow) return 1;
    double x = eta * u - eta + 1.0;
    uint64_t r = (uint64_t)((double)n * fast_exp2(alpha * fast_log2(x)));
    return r >= n ? n - 1 : r;
  }
};

struct UniformGen {
  uint64_t n;
  Rng rng;
  UniformGen(uint64_t n_, uint64_t seed) : n(n_), rng(seed) {}
  inline uint64_t next() {
    // Lemire-style rejection-free enough for workload gen: 128-bit multiply.
    return (uint64_t)(((__uint128_t)rng.next() * n) >> 64);
  }
};

}  // namespace shn
