// BatchPrep — fused native batch preparation: zipf sample -> key map ->
// duplicate combining (unique + inverse) -> index-cache probe, one pass.
//
// Role parity: the reference's clients generate and issue each op inline in
// the open benchmark loop (test/benchmark.cpp:159-188) — nothing is hoisted
// out of the timed window.  The batched TPU engine's per-batch equivalent of
// that inline work is exactly this pipeline; the former numpy implementation
// (sort-based np.unique + separate router gather, three passes over 4 M
// keys) cost ~670 ms/batch on a 1-core host and was measured separately
// from the device step.  This version is a streaming dedup pass plus a
// pipelined probe pass:
//
//   rank   = zipf.next_fast()                 (inverse-CDF, fast pow)
//   key    = keyspace[rank]  OR  mix64(rank ^ salt)   (synthetic mode:
//            an arithmetic rank->key bijection, the reference benchmark's
//            own convention — its key IS the zipf rank — so no 800 MB
//            random gather sits in the serving loop)
//   slot   = hash-probe(key)                  (epoch-tagged open addressing,
//            16-byte slots so a probe touches ONE cache line, THP-backed,
//            load factor <= .5, software-prefetched in 256-op blocks)
//   new?   -> assign unique id, split key into (hi, lo) int32 words
//   inv[i] = unique id                        (the fan-out map)
//   then: for each fresh unique, probe router table[min(key >> shift,
//         nb-1)] (the CN cache lookup, IndexCache.h:134-184 role) in a
//         second prefetch-pipelined pass over just the uniques.
//
// The hash table is epoch-tagged so per-batch reset is O(1), not a 128 MB
// memset.
#include <sys/mman.h>

#include <new>

#include "zipf.h"

#if defined(__x86_64__)
#include <immintrin.h>
#endif

namespace {

constexpr uint64_t kBlock = 128;

inline uint64_t mix64(uint64_t x) {
  // splitmix64 finalizer — full-avalanche, 3 multiplies
  x ^= x >> 30;
  x *= 0xbf58476d1ce4e5b9ull;
  x ^= x >> 27;
  x *= 0x94d049bb133111ebull;
  x ^= x >> 31;
  return x;
}

// Anonymous mapping (over-allocated so a 2 MB-aligned view fits inside)
// with MADV_HUGEPAGE: the hash table and unique-key scratch are
// random-access; 4 KB pages would pay a TLB walk per probe.  Returns the
// RAW mapping (munmap target); callers align their view into it.
void* big_alloc(size_t bytes, size_t* mapped) {
  const size_t kHuge = 2ull << 20;
  size_t sz = ((bytes + kHuge - 1) & ~(kHuge - 1)) + kHuge;
  void* raw = mmap(nullptr, sz, PROT_READ | PROT_WRITE,
                   MAP_PRIVATE | MAP_ANONYMOUS, -1, 0);
  if (raw == MAP_FAILED) return nullptr;
  madvise(raw, sz, MADV_HUGEPAGE);
  *mapped = sz;
  return raw;
}

template <class T>
T* align_huge(void* raw) {
  const uintptr_t kHuge = 2ull << 20;
  return (T*)(((uintptr_t)raw + kHuge - 1) & ~(kHuge - 1));
}

struct Slot {  // one cache line holds 4 slots; a probe touches one line
  uint64_t key;
  uint32_t epoch;
  uint32_t id;
};
static_assert(sizeof(Slot) == 16, "slot packing");

struct Prep {
  uint64_t* keybuf = nullptr;  // sampled client keys staging
  void* kb_raw = nullptr;
  size_t kb_mapped = 0;
  uint64_t batch;
  uint64_t capacity;   // max unique keys per run (output array length)
  uint64_t slots;      // pow2 >= 2*batch
  uint64_t mask;
  uint64_t salt;       // synthetic rank->key mode when != 0
  uint32_t epoch = 0;
  Slot* tab = nullptr;
  void* tab_raw = nullptr;
  size_t tab_mapped = 0;
  uint64_t* ukeys = nullptr;  // unique keys scratch for the probe pass
  void* uk_raw = nullptr;
  size_t uk_mapped = 0;
  shn::Zipf* zipf = nullptr;
  shn::UniformGen* uni = nullptr;
  bool ok = false;

  Prep(uint64_t n_keys, double theta, uint64_t seed, uint64_t batch_,
       uint64_t capacity_, uint64_t salt_)
      : batch(batch_), capacity(capacity_), salt(salt_) {
    slots = 64;
    while (slots < 2 * batch) slots <<= 1;
    mask = slots - 1;
    tab_raw = big_alloc(slots * sizeof(Slot), &tab_mapped);
    uk_raw = big_alloc(capacity * sizeof(uint64_t), &uk_mapped);
    kb_raw = big_alloc(batch * sizeof(uint64_t), &kb_mapped);
    if (!tab_raw || !uk_raw || !kb_raw) return;
    keybuf = align_huge<uint64_t>(kb_raw);
    tab = align_huge<Slot>(tab_raw);
    ukeys = align_huge<uint64_t>(uk_raw);
    memset(tab, 0, slots * sizeof(Slot));  // epoch 0 = never-used
    if (n_keys) {
      if (theta > 0.0)
        zipf = new (std::nothrow) shn::Zipf(n_keys, theta, seed);
      else
        uni = new (std::nothrow) shn::UniformGen(n_keys, seed);
      if (!zipf && !uni) return;
    }
    ok = true;
  }

  ~Prep() {
    if (tab_raw) munmap(tab_raw, tab_mapped);
    if (uk_raw) munmap(uk_raw, uk_mapped);
    if (kb_raw) munmap(kb_raw, kb_mapped);
    delete zipf;
    delete uni;
  }

  inline void bump_epoch() {
    if (++epoch == 0) {  // wrapped: one real reset every 2^32 batches
      memset(tab, 0, slots * sizeof(Slot));
      epoch = 1;
    }
  }

  // Dedup the generated stream.  Gen yields the next client key (stateful;
  // gather-style generators prefetch their own lookahead).  A rolling
  // D-deep software pipeline keeps ~D probe lines in flight continuously —
  // burst-phase (generate-all-then-probe-all) pipelining measured ~70 ms
  // slower per 4 M batch: the probe burst stalls on whatever the burst of
  // prefetches had not finished, while the generator sits idle.
  // Returns n_unique or -1 on capacity overflow.
  template <class Gen>
  int64_t dedup(Gen&& gen, uint64_t n, int32_t* khi, int32_t* klo,
                int32_t* inv) {
    bump_epoch();
    const uint32_t cur = epoch;
    uint64_t nu = 0;
    constexpr uint64_t D = 32;  // pipeline depth ~ MSHR budget
    uint64_t kq[D], hq[D];
    const uint64_t fill = n < D ? n : D;
    for (uint64_t j = 0; j < fill; ++j) {
      const uint64_t k = gen();
      kq[j] = k;
      hq[j] = mix64(k) & mask;
      __builtin_prefetch(&tab[hq[j]], 0, 1);
    }
    for (uint64_t i = 0; i < n; ++i) {
      const uint64_t r = i % D;
      const uint64_t k = kq[r];
      uint64_t s = hq[r];
      if (i + D < n) {  // refill the ring before probing (issue the miss)
        const uint64_t k2 = gen();
        kq[r] = k2;
        hq[r] = mix64(k2) & mask;
        __builtin_prefetch(&tab[hq[r]], 0, 1);
      }
      for (;;) {
        Slot& sl = tab[s];
        if (sl.epoch != cur) {  // empty this batch: claim
          if (nu >= capacity) return -1;
          sl.epoch = cur;
          sl.key = k;
          sl.id = (uint32_t)nu;
          ukeys[nu] = k;
          khi[nu] = (int32_t)(uint32_t)(k >> 32);
          klo[nu] = (int32_t)(uint32_t)k;
          inv[i] = (int32_t)nu;
          ++nu;
          break;
        }
        if (sl.key == k) {
          inv[i] = (int32_t)sl.id;
          break;
        }
        s = (s + 1) & mask;
      }
    }
    return (int64_t)nu;
  }

  // Router-table probe over just the uniques, prefetch-pipelined.
  void probe(uint64_t nu, const int32_t* table, uint64_t nb, uint32_t shift,
             int32_t default_start, int32_t* start) {
    if (!table) {
      for (uint64_t i = 0; i < nu; ++i) start[i] = default_start;
      return;
    }
    uint64_t b[kBlock];
    for (uint64_t base = 0; base < nu; base += kBlock) {
      const uint64_t m = (nu - base < kBlock) ? nu - base : kBlock;
      for (uint64_t j = 0; j < m; ++j) {
        uint64_t bk = ukeys[base + j] >> shift;
        if (bk >= nb) bk = nb - 1;
        b[j] = bk;
        __builtin_prefetch(&table[bk], 0, 1);
      }
      for (uint64_t j = 0; j < m; ++j) start[base + j] = table[b[j]];
    }
  }

  int64_t finish(int64_t nu_s, const int32_t* table, uint64_t nb,
                 uint32_t shift, int32_t default_start, int32_t* khi,
                 int32_t* klo, int32_t* start, uint8_t* active) {
    if (nu_s < 0) return nu_s;
    const uint64_t nu = (uint64_t)nu_s;
    probe(nu, table, nb, shift, default_start, start);
    memset(active, 0, capacity);
    memset(active, 1, nu);
    // pad rows: inactive, but give them a harmless in-bounds start seed
    for (uint64_t i = nu; i < capacity; ++i) {
      khi[i] = 0;
      klo[i] = 0;
      start[i] = default_start;
    }
    return nu_s;
  }
};

inline uint64_t sample_one(shn::Zipf* z) { return z->next_fast(); }
inline uint64_t sample_one(shn::UniformGen* u) { return u->next(); }

#if defined(__x86_64__)
// 8-wide AVX-512 zipf sampler fused with the synthetic key map: rank ->
// mix64(rank ^ salt).  The scalar pow chain costs ~26 ns/sample and is the
// prep bottleneck (measured 108 ms of a ~205 ms 4 M-op batch); this runs
// the whole inverse-CDF (exponent-extract log2 with sqrt2 range reduction
// + deg-10 polynomial, exp2 as floor + deg-7 polynomial + exponent
// assembly) and the splitmix64 finisher on 8 lanes of independent
// xorshift128+ streams.  Lane seeds derive from the generator's scalar
// RNG, so the stream stays deterministic per (seed, call sequence).
// Polynomial abs err: log2 1.2e-9, exp2 5.8e-11 -> rank relative error
// ~1e-6 at theta=0.99 (alpha ~= 100) — far inside workload-gen tolerance
// (the reference's MICA sampler uses a coarser fast-pow).
__attribute__((target("avx512f,avx512dq")))
void zipf_fill_keys_avx512(shn::Zipf* z, uint64_t salt, uint64_t n,
                           uint64_t* out) {
  alignas(64) uint64_t seed[16];
  for (int l = 0; l < 16; ++l) seed[l] = z->rng.next();
  __m512i s0 = _mm512_load_si512(seed);
  __m512i s1 = _mm512_load_si512(seed + 8);
  const __m512d vzetan = _mm512_set1_pd(z->zetan);
  const __m512d vhalf = _mm512_set1_pd(z->half_pow);
  const __m512d veta = _mm512_set1_pd(z->eta);
  const __m512d v1me = _mm512_set1_pd(1.0 - z->eta);
  const __m512d valpha = _mm512_set1_pd(z->alpha);
  const __m512d vn = _mm512_set1_pd((double)z->n);
  const __m512d vnm1 = _mm512_set1_pd((double)(z->n - 1));
  const __m512d vsqrt2 = _mm512_set1_pd(1.4142135623730951);
  const __m512d vhalfc = _mm512_set1_pd(0.5);
  const __m512d v2_53 = _mm512_set1_pd(1.0 / 9007199254740992.0);
  const __m512i vmant = _mm512_set1_epi64(0x000fffffffffffffull);
  const __m512i vonee = _mm512_set1_epi64(0x3ff0000000000000ull);
  const __m512i v1023 = _mm512_set1_epi64(1023);
  const __m512i vsalt = _mm512_set1_epi64((long long)salt);
  const __m512i vc1 = _mm512_set1_epi64((long long)0xbf58476d1ce4e5b9ull);
  const __m512i vc2 = _mm512_set1_epi64((long long)0x94d049bb133111ebull);
  // log2(1+z) on [1/sqrt2-1, sqrt2-1], low->high (fit err 1.2e-9)
  const double L[11] = {-9.953058253149826e-10, 1.442695036014125,
                        -0.7213470203588495,    0.48089872672209055,
                        -0.3607143286287836,    0.2885602359470694,
                        -0.23929769546910243,   0.20452211479439902,
                        -0.19315336620869378,   0.18741281050237493,
                        -0.10700663883393477};
  // 2^f on [0,1), low->high (fit err 5.8e-11)
  const double E[8] = {0.999999999943856,      0.6931471877102315,
                       0.24022635776975182,    0.05550529197743555,
                       0.009613535732759894,   0.001342981070631923,
                       0.0001429940125774305,  2.1651724410663057e-05};
  uint64_t i = 0;
  for (; i + 8 <= n; i += 8) {
    // xorshift128+ (8 lanes)
    __m512i x = s0;
    const __m512i y = s1;
    s0 = y;
    x = _mm512_xor_si512(x, _mm512_slli_epi64(x, 23));
    s1 = _mm512_xor_si512(
        _mm512_xor_si512(_mm512_xor_si512(x, y), _mm512_srli_epi64(x, 17)),
        _mm512_srli_epi64(y, 26));
    const __m512i r64 = _mm512_add_epi64(s1, y);
    // u in [0, 1)
    const __m512d u = _mm512_mul_pd(
        _mm512_cvtepi64_pd(_mm512_srli_epi64(r64, 11)), v2_53);
    const __m512d uz = _mm512_mul_pd(u, vzetan);
    const __m512d xv = _mm512_fmadd_pd(veta, u, v1me);  // in (1-eta, 1]
    // log2(xv): exponent + mantissa poly with sqrt2 range reduction
    const __m512i bits = _mm512_castpd_si512(xv);
    __m512i eI = _mm512_sub_epi64(_mm512_srli_epi64(bits, 52), v1023);
    __m512d m = _mm512_castsi512_pd(
        _mm512_or_si512(_mm512_and_si512(bits, vmant), vonee));
    const __mmask8 big = _mm512_cmp_pd_mask(m, vsqrt2, _CMP_GT_OQ);
    m = _mm512_mask_mul_pd(m, big, m, vhalfc);
    eI = _mm512_mask_add_epi64(eI, big, eI, _mm512_set1_epi64(1));
    const __m512d zq = _mm512_sub_pd(m, _mm512_set1_pd(1.0));
    __m512d p = _mm512_set1_pd(L[10]);
    for (int c = 9; c >= 0; --c)
      p = _mm512_fmadd_pd(p, zq, _mm512_set1_pd(L[c]));
    const __m512d l2 = _mm512_add_pd(_mm512_cvtepi64_pd(eI), p);
    // exp2(alpha * l2)
    const __m512d yv = _mm512_mul_pd(valpha, l2);  // in [~-28, 0]
    const __m512d fi =
        _mm512_roundscale_pd(yv, _MM_FROUND_TO_NEG_INF | _MM_FROUND_NO_EXC);
    const __m512d f = _mm512_sub_pd(yv, fi);
    __m512d ef = _mm512_set1_pd(E[7]);
    for (int c = 6; c >= 0; --c)
      ef = _mm512_fmadd_pd(ef, f, _mm512_set1_pd(E[c]));
    const __m512d scale = _mm512_castsi512_pd(_mm512_slli_epi64(
        _mm512_add_epi64(_mm512_cvtpd_epi64(fi), v1023), 52));
    __m512d rank_d = _mm512_mul_pd(vn, _mm512_mul_pd(ef, scale));
    rank_d = _mm512_min_pd(rank_d, vnm1);
    __m512i rank = _mm512_cvttpd_epi64(rank_d);
    // head special cases (uz < 1 -> 0; uz < 1 + 0.5^theta -> 1)
    const __mmask8 m1 = _mm512_cmp_pd_mask(uz, vhalf, _CMP_LT_OQ);
    const __mmask8 m0 = _mm512_cmp_pd_mask(uz, _mm512_set1_pd(1.0),
                                           _CMP_LT_OQ);
    rank = _mm512_mask_mov_epi64(rank, m1, _mm512_set1_epi64(1));
    rank = _mm512_mask_mov_epi64(rank, m0, _mm512_setzero_si512());
    // key = mix64(rank ^ salt)
    __m512i k = _mm512_xor_si512(rank, vsalt);
    k = _mm512_xor_si512(k, _mm512_srli_epi64(k, 30));
    k = _mm512_mullo_epi64(k, vc1);
    k = _mm512_xor_si512(k, _mm512_srli_epi64(k, 27));
    k = _mm512_mullo_epi64(k, vc2);
    k = _mm512_xor_si512(k, _mm512_srli_epi64(k, 31));
    _mm512_storeu_si512(out + i, k);
  }
  for (; i < n; ++i) out[i] = mix64(z->next_fast() ^ salt);
}

inline bool have_avx512() {
  static const bool ok = __builtin_cpu_supports("avx512f") &&
                         __builtin_cpu_supports("avx512dq");
  return ok;
}
#endif  // __x86_64__

// Stage A for synthetic zipf mode: vectorized when the CPU allows.
inline void fill_synthetic_zipf(shn::Zipf* z, uint64_t salt, uint64_t n,
                                uint64_t* out) {
#if defined(__x86_64__)
  if (have_avx512()) {
    zipf_fill_keys_avx512(z, salt, n, out);
    return;
  }
#endif
  for (uint64_t i = 0; i < n; ++i) out[i] = mix64(z->next_fast() ^ salt);
}

// Stateful generator: samples ranks R ahead and prefetches the keyspace
// gather targets, so by the time a rank's key is consumed its cache line
// is (usually) resident.
template <class Sampler>
struct RankAhead {
  Sampler* s;
  const uint64_t* keyspace;
  static constexpr uint64_t R = 16;
  uint64_t ring[R];
  uint64_t head = 0;

  RankAhead(Sampler* s_, const uint64_t* ks) : s(s_), keyspace(ks) {
    for (uint64_t j = 0; j < R; ++j) {
      ring[j] = sample_one(s);
      __builtin_prefetch(&keyspace[ring[j]], 0, 1);
    }
  }

  inline uint64_t operator()() {
    const uint64_t r = ring[head];
    ring[head] = sample_one(s);
    __builtin_prefetch(&keyspace[ring[head]], 0, 1);
    head = (head + 1) % R;
    return keyspace[r];
  }
};

}  // namespace

SHN_EXPORT void* shn_prep_new(uint64_t n_keys, double theta, uint64_t seed,
                              uint64_t batch, uint64_t capacity,
                              uint64_t salt) {
  if (batch == 0 || capacity == 0) return nullptr;
  auto* p = new (std::nothrow) Prep(n_keys, theta, seed, batch, capacity,
                                    salt);
  if (p && !p->ok) {
    delete p;
    return nullptr;
  }
  return p;
}

SHN_EXPORT void shn_prep_free(void* h) { delete (Prep*)h; }

// Phase-attribution hook: run stage A (sampling) alone.  Benchmarks only.
SHN_EXPORT int64_t shn_prep_sample_only(void* h) {
  auto* p = (Prep*)h;
  if (!p || !p->salt || (!p->zipf && !p->uni)) return -2;
  uint64_t* kb = p->keybuf;
  const uint64_t n = p->batch;
  const uint64_t salt = p->salt;
  if (p->zipf) {
    fill_synthetic_zipf(p->zipf, salt, n, kb);
  } else {
    auto* u = p->uni;
    for (uint64_t i = 0; i < n; ++i) kb[i] = mix64(u->next() ^ salt);
  }
  return (int64_t)n;
}

SHN_EXPORT int64_t shn_prep_run_keys(void* h, const uint64_t* keys,
                                     uint64_t n, const int32_t* table,
                                     uint64_t nb, uint32_t shift,
                                     int32_t default_start, int32_t* khi,
                                     int32_t* klo, int32_t* start,
                                     uint8_t* active, int32_t* inv) {
  auto* p = (Prep*)h;
  if (!p || n > p->batch) return -2;
  uint64_t i = 0;
  int64_t nu = p->dedup([keys, &i]() { return keys[i++]; }, n, khi, klo,
                        inv);
  return p->finish(nu, table, nb, shift, default_start, khi, klo, start,
                   active);
}

SHN_EXPORT int64_t shn_prep_run_zipf(void* h, const uint64_t* keyspace,
                                     uint64_t* out_keys,
                                     const int32_t* table, uint64_t nb,
                                     uint32_t shift, int32_t default_start,
                                     int32_t* khi, int32_t* klo,
                                     int32_t* start, uint8_t* active,
                                     int32_t* inv) {
  auto* p = (Prep*)h;
  if (!p || (!p->zipf && !p->uni)) return -2;
  if (!keyspace && !p->salt) return -2;
  // Stage A: sample the whole batch into the staging buffer in a TIGHT
  // loop (the pow polynomial keeps every register; fusing it into the
  // probe loop measured ~70 ms/batch slower from spill pressure), then
  // Stage B: dedup streams the staging buffer like an external key batch.
  uint64_t* kb = p->keybuf;
  const uint64_t n = p->batch;
  if (keyspace && p->zipf) {
    // internal rank lookahead so the keyspace gather is prefetched
    RankAhead<shn::Zipf> g{p->zipf, keyspace};
    for (uint64_t i = 0; i < n; ++i) kb[i] = g();
  } else if (keyspace) {
    RankAhead<shn::UniformGen> g{p->uni, keyspace};
    for (uint64_t i = 0; i < n; ++i) kb[i] = g();
  } else if (p->zipf) {
    fill_synthetic_zipf(p->zipf, p->salt, n, kb);
  } else {
    const uint64_t salt = p->salt;
    auto* u = p->uni;
    for (uint64_t i = 0; i < n; ++i) kb[i] = mix64(u->next() ^ salt);
  }
  if (out_keys) memcpy(out_keys, kb, n * sizeof(uint64_t));
  uint64_t i = 0;
  int64_t nu = p->dedup([kb, &i]() { return kb[i++]; }, n, khi, klo,
                        inv);
  return p->finish(nu, table, nb, shift, default_start, khi, klo, start,
                   active);
}
