// Local hierarchical lock table — ticket locks with bounded hand-over.
//
// Role parity: Sherman technique #1's local tier (Tree.cpp:1124-1173 +
// LocalLockNode, Tree.h:12-16): same-node contention on a global lock
// collapses onto a node-local ticket lock; the holder may hand the lock
// to the next local waiter up to kMaxHandOverTime=8 times (Common.h:101),
// so only one global CAS is paid per hand-over train.
//
// acquire(i) blocks (spin) until the caller holds local lock i, returning
// 1 when the *global* lock was handed over with it (skip the remote CAS).
// release(i, handover_ok) decides whether to pass the global lock on.
#include <new>

#include "common.h"

namespace {

constexpr uint32_t kMaxHandOver = 8;  // Common.h:101 parity

inline void cpu_relax() {
#if defined(__x86_64__)
  __builtin_ia32_pause();
#else
  std::atomic_signal_fence(std::memory_order_seq_cst);
#endif
}

struct alignas(64) LocalLock {
  std::atomic<uint32_t> ticket{0};
  std::atomic<uint32_t> current{0};
  // written only by the holder, read by the next holder under the ticket
  // ordering, so plain fields are fine with acq/rel on `current`
  uint8_t handed_over{0};
  uint32_t hand_time{0};
};

struct LockTable {
  uint64_t n;
  LocalLock* locks;
  explicit LockTable(uint64_t n_) : n(n_) {
    locks = new (std::nothrow) LocalLock[n];
  }
  ~LockTable() { delete[] locks; }
};

}  // namespace

SHN_EXPORT void* shn_lt_new(uint64_t n_locks) {
  auto* t = new (std::nothrow) LockTable(n_locks);
  if (t && !t->locks) {  // inner array alloc failed: report, don't segfault
    delete t;
    return nullptr;
  }
  return t;
}

SHN_EXPORT void shn_lt_free(void* h) { delete (LockTable*)h; }

// Blocks until local lock i is held; -> 1 if the global lock came with it.
SHN_EXPORT int shn_lt_acquire(void* h, uint64_t i) {
  auto& l = ((LockTable*)h)->locks[i];
  uint32_t my = l.ticket.fetch_add(1, std::memory_order_relaxed);
  while (l.current.load(std::memory_order_acquire) != my) {
    cpu_relax();  // holders run whole DSM steps; don't starve their core
  }
  return l.handed_over ? 1 : 0;
}

// Holder-only probe: would release(handover_ok=1) hand the lock over right
// now?  Lets the holder decide BEFORE its protected write step whether to
// coalesce the global unlock into the step (no waiter) or omit it (a
// hand-over train keeps the global lock).  The answer can only flip
// false -> true between probe and release (ticket waiters block and only
// the holder writes hand_time), so: probe true  -> release(1) is
// guaranteed to hand over; probe false -> caller coalesced the global
// unlock and must call release(0) so a late-arriving waiter is NOT handed
// a global lock that was just released.
SHN_EXPORT int shn_lt_can_handover(void* h, uint64_t i) {
  auto& l = ((LockTable*)h)->locks[i];
  uint32_t my = l.current.load(std::memory_order_relaxed);
  uint32_t next = l.ticket.load(std::memory_order_acquire);
  return (next != my + 1 && l.hand_time < kMaxHandOver) ? 1 : 0;
}

// Release local lock i.  handover_ok != 0 when the caller is willing to
// pass the global lock on.  -> 1 if handed over (caller must NOT release
// the global lock), 0 otherwise (caller releases the global lock).
SHN_EXPORT int shn_lt_release(void* h, uint64_t i, int handover_ok) {
  auto& l = ((LockTable*)h)->locks[i];
  uint32_t my = l.current.load(std::memory_order_relaxed);
  uint32_t next = l.ticket.load(std::memory_order_acquire);
  // hand over only if someone is waiting and the train isn't too long
  // (can_hand_over, Tree.cpp:1149-1167)
  bool waiter = next != my + 1;
  bool pass = handover_ok && waiter && l.hand_time < kMaxHandOver;
  if (pass) {
    l.handed_over = 1;
    l.hand_time++;
  } else {
    l.handed_over = 0;
    l.hand_time = 0;
  }
  l.current.store(my + 1, std::memory_order_release);
  return pass ? 1 : 0;
}

// ---------------------------------------------------------------------------
// WRLock — spinning writer-preference reader/writer lock (WRLock.h parity:
// the reference guards its DSM singleton and the IndexCache delay-free list
// with it).  Writers announce intent via the high bit; new readers then
// spin until the writer cycles through.
// ---------------------------------------------------------------------------

namespace {

struct WRLock {
  static constexpr uint32_t kWriter = 1u << 31;
  std::atomic<uint32_t> state{0};  // kWriter bit | reader count
};

}  // namespace

SHN_EXPORT void* shn_rw_new() { return new (std::nothrow) WRLock(); }
SHN_EXPORT void shn_rw_free(void* h) { delete (WRLock*)h; }

SHN_EXPORT void shn_rw_rlock(void* h) {
  auto& s = ((WRLock*)h)->state;
  for (;;) {
    uint32_t v = s.load(std::memory_order_relaxed);
    if (!(v & WRLock::kWriter) &&
        s.compare_exchange_weak(v, v + 1, std::memory_order_acquire))
      return;
    cpu_relax();
  }
}

SHN_EXPORT void shn_rw_runlock(void* h) {
  ((WRLock*)h)->state.fetch_sub(1, std::memory_order_release);
}

SHN_EXPORT void shn_rw_wlock(void* h) {
  auto& s = ((WRLock*)h)->state;
  // announce writer intent (writer preference: blocks new readers)...
  for (;;) {
    uint32_t v = s.load(std::memory_order_relaxed);
    if (!(v & WRLock::kWriter) &&
        s.compare_exchange_weak(v, v | WRLock::kWriter,
                                std::memory_order_acquire))
      break;
    cpu_relax();
  }
  // ...then drain the readers
  while (s.load(std::memory_order_acquire) != WRLock::kWriter) cpu_relax();
}

SHN_EXPORT void shn_rw_wunlock(void* h) {
  ((WRLock*)h)->state.store(0, std::memory_order_release);
}
