#!/usr/bin/env python
"""Decompose the device-staged PREP pipeline on the real chip.

Builds cumulative cut-down versions of the prep program (PRNG only ->
+zipf table gather -> +mix64 -> +pair sort -> +flag-sort compaction ->
+router probe = full) and times each; the successive deltas price every
phase.  Informs the sustained-loop optimization (BENCHMARKS.md round-5
section): prep serializes with the serve on one chip, so every ms cut
here is ms off the sustained step.

Env: KEYS (default 10_000_000), B (batch, default 4_194_304), K (reps).
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    jax.config.update("jax_compilation_cache_dir", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache"))

    from sherman_tpu.ops import bits
    from sherman_tpu.workload.device_prep import (
        _gen_ranks, _keys_of_ranks, _router_probe, _sort_combine,
        zipf_table)

    n_keys = int(os.environ.get("KEYS", 10_000_000))
    batch = int(os.environ.get("B", 4_194_304))
    K = int(os.environ.get("K", 16))
    theta = 0.99
    salt = 0x5E17_AB1E_5A17
    LB = int(os.environ.get("LB", 20))
    dev_b = int(os.environ.get("DEVB", 1_114_112))
    salt_hi = np.uint32((salt >> 32) & 0xFFFFFFFF)
    salt_lo = np.uint32(salt & 0xFFFFFFFF)

    t = zipf_table(n_keys, theta, LB)
    tpair = jax.device_put(np.stack([t[:-1], t[1:]], axis=1))
    # stand-in router table (the probe is one gather from an int32 table
    # of this size; content does not affect its cost)
    rt_size = int(os.environ.get("RT", 1 << 24))
    rtable = jax.device_put(np.zeros(rt_size, np.int32))
    rkey = jax.device_put(jax.random.PRNGKey(11))

    # cumulative stages call the SHIPPED device_prep helpers — a change
    # to the production pipeline is automatically what gets priced here
    def stage_prng(rk, si):
        k = jax.random.fold_in(rk, si)
        return jax.random.bits(k, (2, batch), dtype=jnp.uint32)

    def stage_rank(rk, si):
        return _gen_ranks(tpair, stage_prng(rk, si), log2_bins=LB,
                          n_keys=n_keys)

    def stage_mix(rk, si):
        return _keys_of_ranks(stage_rank(rk, si), salt_hi, salt_lo)

    def stage_sort(rk, si):
        khi, klo = stage_mix(rk, si)
        return lax.sort((khi, klo), num_keys=2)

    def stage_compact(rk, si):
        khi, klo = stage_mix(rk, si)
        skhi, sklo, ukhi, uklo, seg, n_uniq = _sort_combine(
            khi, klo, dev_b)
        return ukhi, uklo, seg

    def stage_full(rk, si):
        ukhi, uklo, seg = stage_compact(rk, si)
        return _router_probe(rtable, ukhi, uklo, 20, rt_size), seg

    # --- rank-sort alternative: 1-op sort + 2-op flag sort, mix64 and
    # probe on the unique set only; clients served in rank-sorted order
    def stage_ranksort_full(rk, si):
        rank = stage_rank(rk, si)
        srank = lax.sort(rank)
        first = jnp.concatenate([
            jnp.ones((1,), jnp.int32),
            (srank[1:] != srank[:-1]).astype(jnp.int32)])
        seg = (jnp.cumsum(first) - 1).astype(jnp.int32)
        _, crank = lax.sort((jnp.int32(1) - first, srank), num_keys=2)
        ur = crank[:dev_b]
        xlo = lax.bitcast_convert_type(ur, jnp.uint32) ^ salt_lo
        xhi = jnp.full((dev_b,), salt_hi, jnp.uint32)
        ukhi, uklo = bits.mix64_pair(xhi, xlo)
        bhi, blo = bits.u64_shr(ukhi, uklo, 20)
        bucket = jnp.where(bhi != 0, jnp.uint32(rt_size - 1),
                           jnp.minimum(blo, jnp.uint32(rt_size - 1)))
        # client keys for the verification compare: monotone gather from
        # the unique rows
        ckh = jnp.take_along_axis(ukhi, jnp.clip(seg, 0, dev_b - 1), 0)
        ckl = jnp.take_along_axis(uklo, jnp.clip(seg, 0, dev_b - 1), 0)
        return rtable[bucket.astype(jnp.int32)], seg, ckh, ckl

    stages = [
        ("prng(2xB)", stage_prng),
        ("+zipf gather", stage_rank),
        ("+mix64", stage_mix),
        ("+pair sort", stage_sort),
        ("+flag compact", stage_compact),
        ("+router probe", stage_full),
        ("ranksort FULL", stage_ranksort_full),
    ]
    prev = 0.0
    for name, fn in stages:
        j = jax.jit(fn)
        out = j(rkey, np.uint32(0))
        jax.block_until_ready(out)
        t0 = time.time()
        for i in range(K):
            out = j(rkey, np.uint32(i))
        jax.block_until_ready(out)
        ms = (time.time() - t0) / K * 1e3
        print(f"{name:16s} {ms:8.1f} ms  (delta {ms - prev:+7.1f})",
              flush=True)
        prev = ms


if __name__ == "__main__":
    main()
