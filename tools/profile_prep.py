#!/usr/bin/env python
"""Price the request-plane prep: host vs device A/B + chip stage deltas.

Two modes:

* default (``main()``): the PR 17 host-vs-device A/B.  Builds a small
  engine, constructs the SHIPPED ingress step twice (``prep_impl=host``
  and ``prep_impl=device``), and prices the prep phase of each with the
  same chained-delta discipline every phase receipt uses
  (``step.prep_profile``).  Also runs a duplicate-leaf write batch
  through the write-combining kernel and publishes the measured combine
  ratio (``combine.locks_saved / lock-acquisitions-uncombined``).  The
  last stdout line is the JSON receipt BENCHMARKS rounds consume;
  ``main()`` returns the same dict (the test_tools driver contract).

* ``--stages`` (or ``MODE=stages``): the round-5 cumulative cut-down
  profiler of the device-staged PREP pipeline (PRNG -> +zipf gather ->
  +mix64 -> +pair sort -> +flag compact -> +router probe); successive
  deltas price every phase on the real chip.

Env: KEYS (default 20_000), W (ingress width, default 1024), K (reps,
default 8), DUP (combine-batch duplication factor, default 8).  Stage
mode keeps its own knobs (KEYS, B, DEVB, K, LB, RT).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def stage_deltas():
    """Cumulative cut-down stage profiler (chip mode; prints a table)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    jax.config.update("jax_compilation_cache_dir", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache"))

    from sherman_tpu.ops import bits
    from sherman_tpu.workload.device_prep import (
        _gen_ranks, _keys_of_ranks, _router_probe, _sort_combine,
        zipf_table)

    n_keys = int(os.environ.get("KEYS", 10_000_000))
    batch = int(os.environ.get("B", 4_194_304))
    K = int(os.environ.get("K", 16))
    theta = 0.99
    salt = 0x5E17_AB1E_5A17
    LB = int(os.environ.get("LB", 20))
    dev_b = int(os.environ.get("DEVB", 1_114_112))
    salt_hi = np.uint32((salt >> 32) & 0xFFFFFFFF)
    salt_lo = np.uint32(salt & 0xFFFFFFFF)

    t = zipf_table(n_keys, theta, LB)
    tpair = jax.device_put(np.stack([t[:-1], t[1:]], axis=1))
    # stand-in router table (the probe is one gather from an int32 table
    # of this size; content does not affect its cost)
    rt_size = int(os.environ.get("RT", 1 << 24))
    rtable = jax.device_put(np.zeros(rt_size, np.int32))
    rkey = jax.device_put(jax.random.PRNGKey(11))

    # cumulative stages call the SHIPPED device_prep helpers — a change
    # to the production pipeline is automatically what gets priced here
    def stage_prng(rk, si):
        k = jax.random.fold_in(rk, si)
        return jax.random.bits(k, (2, batch), dtype=jnp.uint32)

    def stage_rank(rk, si):
        return _gen_ranks(tpair, stage_prng(rk, si), log2_bins=LB,
                          n_keys=n_keys)

    def stage_mix(rk, si):
        return _keys_of_ranks(stage_rank(rk, si), salt_hi, salt_lo)

    def stage_sort(rk, si):
        khi, klo = stage_mix(rk, si)
        return lax.sort((khi, klo), num_keys=2)

    def stage_compact(rk, si):
        khi, klo = stage_mix(rk, si)
        skhi, sklo, ukhi, uklo, seg, n_uniq = _sort_combine(
            khi, klo, dev_b)
        return ukhi, uklo, seg

    def stage_full(rk, si):
        ukhi, uklo, seg = stage_compact(rk, si)
        return _router_probe(rtable, ukhi, uklo, 20, rt_size), seg

    # --- rank-sort alternative: 1-op sort + 2-op flag sort, mix64 and
    # probe on the unique set only; clients served in rank-sorted order
    def stage_ranksort_full(rk, si):
        rank = stage_rank(rk, si)
        srank = lax.sort(rank)
        first = jnp.concatenate([
            jnp.ones((1,), jnp.int32),
            (srank[1:] != srank[:-1]).astype(jnp.int32)])
        seg = (jnp.cumsum(first) - 1).astype(jnp.int32)
        _, crank = lax.sort((jnp.int32(1) - first, srank), num_keys=2)
        ur = crank[:dev_b]
        xlo = lax.bitcast_convert_type(ur, jnp.uint32) ^ salt_lo
        xhi = jnp.full((dev_b,), salt_hi, jnp.uint32)
        ukhi, uklo = bits.mix64_pair(xhi, xlo)
        bhi, blo = bits.u64_shr(ukhi, uklo, 20)
        bucket = jnp.where(bhi != 0, jnp.uint32(rt_size - 1),
                           jnp.minimum(blo, jnp.uint32(rt_size - 1)))
        # client keys for the verification compare: monotone gather from
        # the unique rows
        ckh = jnp.take_along_axis(ukhi, jnp.clip(seg, 0, dev_b - 1), 0)
        ckl = jnp.take_along_axis(uklo, jnp.clip(seg, 0, dev_b - 1), 0)
        return rtable[bucket.astype(jnp.int32)], seg, ckh, ckl

    stages = [
        ("prng(2xB)", stage_prng),
        ("+zipf gather", stage_rank),
        ("+mix64", stage_mix),
        ("+pair sort", stage_sort),
        ("+flag compact", stage_compact),
        ("+router probe", stage_full),
        ("ranksort FULL", stage_ranksort_full),
    ]
    prev = 0.0
    for name, fn in stages:
        j = jax.jit(fn)
        out = j(rkey, np.uint32(0))
        jax.block_until_ready(out)
        t0 = time.time()
        for i in range(K):
            out = j(rkey, np.uint32(i))
        jax.block_until_ready(out)
        ms = (time.time() - t0) / K * 1e3
        print(f"{name:16s} {ms:8.1f} ms  (delta {ms - prev:+7.1f})",
              flush=True)
        prev = ms


def _make_engine(n, *, write_combine=False):
    from sherman_tpu.cluster import Cluster
    from sherman_tpu.config import DSMConfig, TreeConfig
    from sherman_tpu.models import batched
    from sherman_tpu.models.btree import Tree

    cfg = DSMConfig(machine_nr=1,
                    pages_per_node=max(2048, n // 8),
                    locks_per_node=512, step_capacity=1024,
                    chunk_pages=32)
    tree = Tree(Cluster(cfg))
    keys = np.arange(100, 100 + n * 3, 3, dtype=np.uint64)
    vals = keys * np.uint64(7)
    batched.bulk_load(tree, keys, vals)
    eng = batched.BatchedEngine(
        tree, batch_per_node=256,
        tcfg=TreeConfig(sibling_chase_budget=2),
        write_combine=write_combine)
    eng.attach_router()
    return eng, keys, vals


def main():
    if "--stages" in sys.argv[1:] or os.environ.get("MODE") == "stages":
        stage_deltas()
        return None

    from sherman_tpu.workload.device_prep import make_ingress_step

    n = int(os.environ.get("KEYS", 20_000))
    width = int(os.environ.get("W", 1024))
    reps = int(os.environ.get("K", 8))
    dup = int(os.environ.get("DUP", 8))

    eng, keys, vals = _make_engine(n)
    rng = np.random.default_rng(17)
    batch = rng.choice(keys, size=width, replace=True).astype(np.uint64)

    # -- host-vs-device prep A/B: same batch, same chained-delta timer,
    # the only variable is where combine/sort/route ran
    impls = {}
    for impl in ("host", "device"):
        step = make_ingress_step(eng, width=width, prep_impl=impl)
        prof = step.prep_profile(batch, reps=reps)
        (key, ms), = prof.items()
        # end-to-end ingress step (prep + fused fan-out serve), chained
        t0 = time.perf_counter()
        for _ in range(2):
            step(batch)
        t_warm = time.perf_counter()
        for _ in range(reps):
            step(batch)
        step_ms = (time.perf_counter() - t_warm) / reps * 1e3
        del t0
        impls[impl] = {"prep_ms": round(ms, 4),
                       "step_ms": round(step_ms, 4),
                       "phase_key": key}
        print(f"prep[{impl:6s}]  prep {ms:8.3f} ms   "
              f"full step {step_ms:8.3f} ms", flush=True)

    # -- write-combining ratio on a duplicate-leaf write batch: DUP
    # writers per key land on the same leaf page, so the combined
    # kernel takes one lock per group instead of one per row
    ceng, ckeys, _ = _make_engine(max(2048, n // 4), write_combine=True)
    wk = np.repeat(ckeys[: max(1, 512 // dup)], dup)[:512].astype(np.uint64)
    ceng.insert(wk, wk * np.uint64(3))
    snap = ceng.dsm.counter_snapshot()
    groups = int(snap["combine_groups"])
    saved = int(snap["combine_locks_saved"])
    ratio = saved / (groups + saved) if (groups + saved) else 0.0
    combine = {"groups": groups, "locks_saved": saved,
               "ops_combined": saved,
               "ratio": round(ratio, 4)}
    print(f"combine      groups {groups}  locks_saved {saved}  "
          f"ratio {ratio:.3f}", flush=True)

    out = {
        "metric": "prep_ab",
        "keys": n,
        "width": width,
        "reps": reps,
        "impls": impls,
        "speedup_prep": round(
            impls["host"]["prep_ms"] / impls["device"]["prep_ms"], 3)
        if impls["device"]["prep_ms"] else None,
        "combine": combine,
    }
    print(json.dumps(out), flush=True)
    return out


if __name__ == "__main__":
    main()
