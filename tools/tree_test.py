#!/usr/bin/env python
"""Functional tree driver — ``test/tree_test.cpp`` parity.

The reference inserts 10,239 keys with v=i*2, overwrites with v=i*3,
asserts every search returns the overwrite, deletes a third, asserts the
deletes are gone and the rest intact, re-inserts and re-verifies
(``tree_test.cpp:30-67``).  Same sequence here, driven through BOTH the
batched device path (the production path) and spot-checked through the
host Tree path with the native index cache attached.

    python tools/tree_test.py [kNodeCount] [--n N]
"""

from __future__ import annotations

import argparse

import numpy as np

from common import build_cluster, pages_for_keys, setup_platform


def main(argv=None) -> None:
    p = argparse.ArgumentParser()
    p.add_argument("kNodeCount", type=int, nargs="?", default=1)
    p.add_argument("--n", type=int, default=10_239)
    a = p.parse_args(argv)
    setup_platform(a.kNodeCount)

    from sherman_tpu.utils import Timer, notify_error, notify_info

    n_nodes = a.kNodeCount
    cluster, tree, eng = build_cluster(
        n_nodes, max(4096, pages_for_keys(a.n) // n_nodes), 4096,
        chunk_pages=256)

    rng = np.random.default_rng(3)
    keys = np.unique(rng.integers(1, 1 << 62, int(a.n * 1.2),
                                  dtype=np.uint64))[:a.n]
    assert keys.shape[0] == a.n
    t = Timer()

    # insert v = k*2 (via bulk load: the warmup path), then overwrite v = k*3
    t.begin()
    from sherman_tpu.models import batched
    batched.bulk_load(tree, keys, keys * np.uint64(2))
    eng.attach_router()
    st = eng.insert(keys, keys * np.uint64(3))
    t.end_print(label=f"insert+overwrite {a.n} keys "
                f"(host_path={st['host_path']})")

    got, found = eng.search(keys)
    assert found.all(), f"{(~found).sum()} keys missing after overwrite"
    assert (got == keys * np.uint64(3)).all(), "overwrite not visible"
    notify_info("overwrite verified: v == k*3 for all %d keys", a.n)

    # delete every 3rd key
    dele = keys[::3]
    keep = np.setdiff1d(keys, dele)
    fnd = eng.delete(dele)
    assert fnd.all(), "delete: keys not found"
    _, found = eng.search(dele)
    assert not found.any(), "deleted keys still visible"
    got, found = eng.search(keep)
    assert found.all() and (got == keep * np.uint64(3)).all(), \
        "survivors corrupted by delete"
    notify_info("delete verified: %d gone, %d intact", len(dele), len(keep))

    # re-insert with v = k*5 and final verify
    eng.insert(dele, dele * np.uint64(5))
    got, found = eng.search(dele)
    assert found.all() and (got == dele * np.uint64(5)).all()
    got, found = eng.search(keep)
    assert found.all() and (got == keep * np.uint64(3)).all()

    # host-path spot check with the native index cache attached
    tree.enable_index_cache()
    dele_set = set(map(int, dele))
    for k in map(int, keys[:: max(1, a.n // 64)]):
        want = (k * (5 if k in dele_set else 3)) % (1 << 64)
        v = tree.search(k)
        if v != want:
            notify_error("host search mismatch at %d: %s != %d", k, v, want)
            raise SystemExit(1)

    stats = tree.check_structure()
    notify_info("structure: %s", stats)
    assert stats["keys"] == a.n
    print(f"tree_test PASS ({a.n} keys, {n_nodes} nodes)")


if __name__ == "__main__":
    main()
