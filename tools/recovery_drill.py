#!/usr/bin/env python
"""Recovery drill: traffic -> crash -> recover, with measured RPO/RTO.

The end-to-end rehearsal of the recovery plane (the detection twin is
``tools/chaos_drill.py``):

  phase 1  build + bulk-load a 4-node CPU mesh, start the recovery
           plane: base checkpoint, op journal armed (every acknowledged
           engine write op appends a CRC-framed batch record, covered
           by an fsync before the ack).  The journal runs with
           bounded-delay GROUP COMMIT on (``group_commit_ms`` — env
           ``SHERMAN_DRILL_GC_MS``, default 2.0; 0 restores per-op
           fsync): acks may coalesce into one fsync, but every ack
           still gates on a covering fsync, so the drill's measured
           RPO 0 pins that group commit keeps the contract.
  phase 2  acknowledged traffic: inserts, deletes, a delta checkpoint
           mid-stream (only dirty pages saved), more inserts into the
           live journal segment.
  crash    the cluster is dropped cold.  A torn half-record is appended
           to the journal first — the byte image a crash mid-append
           leaves — and its rows are NOT counted as acknowledged.
  recover  ``RecoveryPlane.recover``: restore base + deltas (epoch
           chain + per-array CRCs verified), replay the journal in
           record order (torn tail truncated, ``journal.truncated_
           tails`` > 0), re-base.  RTO = measured wall time to a
           re-validated serving engine; RPO = acknowledged ops whose
           effect is missing afterwards — asserted ZERO, and the drill
           verifies every acknowledged key/value and every delete.
  phase 3  targeted repair: new traffic, then chaos corruption (torn
           page versions + a flipped entry-version half) on live pages;
           the scrubber quarantines + degrades; ``targeted_repair``
           restores ONLY the damaged pages from the chain, the scrub
           pass re-certifies, degraded mode exits, the journal replay
           catches the repaired pages up — no full-cluster restore
           (asserted via recovery.recovers), keys re-verified.

Runs on the CPU mesh anywhere (``bench.py --recovery-drill`` forwards
here; ``scripts/recovery_ci.sh`` pins it in CI).  Prints ONE JSON line
``{"metric": "recovery_drill", "ok": true, "rpo_ops": 0,
"rto_ms": ...}`` and mirrors it to ``SHERMAN_RECOVERY_RECEIPT`` when
set.  Env knobs: SHERMAN_DRILL_KEYS (default 4000), SHERMAN_DRILL_NODES
(default 4), SHERMAN_CHAOS_SEED (default 7), SHERMAN_DRILL_GC_MS
(journal group-commit window, default 2.0).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

from common import build_cluster, pages_for_keys, setup_platform


def main(argv=None) -> dict:
    p = argparse.ArgumentParser()
    p.add_argument("--keys", type=int,
                   default=int(os.environ.get("SHERMAN_DRILL_KEYS", 4000)))
    p.add_argument("--nodes", type=int,
                   default=int(os.environ.get("SHERMAN_DRILL_NODES", 4)))
    p.add_argument("--seed", type=int,
                   default=int(os.environ.get("SHERMAN_CHAOS_SEED", 7)))
    p.add_argument("--group-commit-ms", type=float,
                   default=float(os.environ.get("SHERMAN_DRILL_GC_MS",
                                                2.0)),
                   help="journal group-commit window (0 = per-op "
                        "fsync); the drill pins RPO 0 with it ON")
    p.add_argument("--dir", default=None,
                   help="recovery directory (default: a tempdir)")
    a = p.parse_args(argv)
    setup_platform(a.nodes)

    from sherman_tpu import chaos as CH
    from sherman_tpu import obs
    from sherman_tpu.config import TreeConfig
    from sherman_tpu.models import batched
    from sherman_tpu.models.scrub import Scrubber
    from sherman_tpu.models.validate import check_structure_device
    from sherman_tpu.recovery import RecoveryPlane

    t_start = time.time()
    out: dict = {"metric": "recovery_drill", "seed": a.seed, "ok": False}
    rdir = a.dir or tempfile.mkdtemp(prefix="sherman_recovery_")
    out["dir"] = rdir

    # -- phase 1: build + arm the recovery plane ------------------------------
    cluster, tree, eng = build_cluster(
        a.nodes, pages_for_keys(a.keys), batch_per_node=512,
        locks_per_node=1024, chunk_pages=64)
    dsm = cluster.dsm
    rng = np.random.default_rng(3)
    keys = np.unique(rng.integers(1, 1 << 56, int(a.keys * 1.05),
                                  dtype=np.uint64))[:a.keys]
    vals = keys ^ np.uint64(0xDEADBEEF)
    batched.bulk_load(tree, keys, vals)
    eng.attach_router()
    check_structure_device(tree)
    plane = RecoveryPlane(cluster, tree, eng, rdir,
                          group_commit_ms=a.group_commit_ms)
    plane.checkpoint_base()
    out["group_commit_ms"] = a.group_commit_ms
    snap0 = obs.snapshot()

    # the acknowledged-op ledger the drill audits RPO against: every
    # (key -> value | DELETED) whose engine op RETURNED before the crash
    acked: dict = {}

    def ack_insert(ks, vs):
        st = eng.insert(ks, vs)
        assert st["lock_timeouts"] == 0
        for k, v in zip(ks.tolist(), vs.tolist()):
            acked[k] = v

    # -- phase 2: acknowledged traffic across a delta boundary ----------------
    nb = max(64, a.keys // 8)
    b1 = keys[:nb]
    ack_insert(b1, b1 ^ np.uint64(0x1111))
    del_keys = keys[nb: nb + nb // 4]
    gone = eng.delete(del_keys)
    assert gone.all()
    for k in del_keys.tolist():
        acked[k] = None
    d1 = plane.checkpoint_delta()
    out["delta1"] = {"pages": d1["pages"], "bytes": d1["bytes"]}
    assert 0 < d1["pages"] < dsm.pool.shape[0], \
        "delta saved nothing or the whole pool"
    b2 = keys[nb + nb // 4: 2 * nb]
    ack_insert(b2, b2 ^ np.uint64(0x2222))

    # -- crash: drop the cluster cold, tear the journal tail ------------------
    jpath = eng.journal.path
    plane.close()
    with open(jpath, "ab") as f:  # a crash mid-append: torn half-record
        from sherman_tpu.utils import journal as J
        rec = J.encode_record(J.J_UPSERT, np.asarray([1 << 40], np.uint64),
                              np.asarray([7], np.uint64))
        f.write(rec[: len(rec) // 2])
    del cluster, tree, eng, dsm

    # -- recover: chain + replay; measure RTO to re-validated serving ---------
    t0 = time.perf_counter()
    plane, cluster, tree, eng, rec = RecoveryPlane.recover(
        rdir, batch_per_node=512,
        tcfg=TreeConfig(sibling_chase_budget=1),
        group_commit_ms=a.group_commit_ms)
    info = check_structure_device(tree)
    rto_ms = (time.perf_counter() - t0) * 1e3
    out["recover"] = rec
    out["rto_ms"] = round(rto_ms, 1)
    obs.gauge("recovery.rto_ms").set(rto_ms)

    # RPO audit: every acknowledged op's effect must be present
    live = {k: v for k, v in acked.items() if v is not None}
    lk = np.asarray(sorted(live), np.uint64)
    got, found = eng.search(lk)
    missing = int((~found).sum()) + int(
        (got[found] != np.asarray([live[int(k)] for k in lk],
                                  np.uint64)[found]).sum())
    dk = np.asarray([k for k, v in acked.items() if v is None], np.uint64)
    if dk.size:
        _, dfound = eng.search(dk)
        missing += int(dfound.sum())  # a deleted key resurfacing = loss
    out["rpo_ops"] = missing
    obs.gauge("recovery.rpo_ops").set(missing)
    assert missing == 0, f"RPO violated: {missing} acknowledged ops lost"
    # untouched bulk keys still intact
    probe = keys[2 * nb:: max(1, a.keys // 512)]
    probe = probe[~np.isin(probe, np.asarray(list(acked), np.uint64))]
    got, found = eng.search(probe)
    assert found.all()
    np.testing.assert_array_equal(got, probe ^ np.uint64(0xDEADBEEF))
    d = obs.delta(snap0, obs.snapshot())
    out["journal"] = {
        "replayed_records": int(d.get("journal.replayed_records", 0)),
        "replayed_rows": int(d.get("journal.replayed_rows", 0)),
        "truncated_tails": int(d.get("journal.truncated_tails", 0)),
        # appends/fsyncs across the drill's acked traffic: > 1 means
        # group commit actually coalesced acks here; RPO 0 above holds
        # REGARDLESS — that is the point of the pin
        "appends": int(d.get("journal.appends", 0)),
        "fsyncs": int(d.get("journal.fsyncs", 0)),
    }
    assert out["journal"]["truncated_tails"] >= 1, \
        "torn tail was not truncated"
    assert info["keys"] > 0

    # -- phase 3: targeted repair exits degraded without a full restore -------
    eng.tcfg = TreeConfig(sibling_chase_budget=1, lock_retry_rounds=2)
    b3 = keys[:nb]
    st = eng.insert(b3, b3 ^ np.uint64(0x3333))
    assert st["lock_timeouts"] == 0
    victim = int(tree._descend(int(keys[a.keys // 2]))[0])
    scr = Scrubber(eng, interval=1)
    assert scr.scrub()["violations"] == 0
    recovers_before = int(obs.snapshot().get("recovery.recovers", 0))
    plan = CH.FaultPlan([
        CH.Fault(kind="torn_page", step=0, addr=victim),
        CH.Fault(kind="flip_entry_ver", step=0, addr=victim, slot=2),
        *CH.FaultPlan.random(a.seed, n_faults=2, step_hi=1).faults,
    ], seed=a.seed)
    cluster.dsm.install_chaos(plan)
    cluster.dsm.read_word(0, 0)
    cluster.dsm.install_chaos(None)
    res = scr.scrub()
    assert res["violations"] >= 1 and eng.degraded
    damage = CH.FaultPlan.rows_to_addrs(
        plan.corrupted_pool_rows(), cluster.cfg.pages_per_node)
    rep = plane.targeted_repair(scr, addrs=damage)
    out["repair"] = {"pages": rep["pages"],
                     "repair_ms": rep["repair_ms"],
                     "replayed": rep["replay"]["records"]}
    assert not eng.degraded, "targeted repair did not exit degraded mode"
    assert int(obs.snapshot().get("recovery.recovers", 0)) \
        == recovers_before, "repair fell back to a full restore"
    check_structure_device(tree)
    got, found = eng.search(b3)
    assert found.all()
    np.testing.assert_array_equal(got, b3 ^ np.uint64(0x3333))
    st = eng.insert(b3[:8], b3[:8])  # writes accepted again
    assert st["applied"] + st["superseded"] == 8

    out["chain"] = {"deltas": len(plane.delta_paths)}
    out["elapsed_s"] = round(time.time() - t_start, 1)
    out["ok"] = True
    plane.close()
    line = json.dumps(out)
    print(line)
    receipt = os.environ.get("SHERMAN_RECOVERY_RECEIPT")
    if receipt:
        with open(receipt, "w") as f:
            f.write(line + "\n")
    print("RECOVERY-DRILL PASS", file=sys.stderr)
    return out


if __name__ == "__main__":
    main()
