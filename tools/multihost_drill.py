#!/usr/bin/env python
"""Multihost drill: per-host chain ownership, the cross-host front
door, union recovery, and the cross-host replication seam.

The sixth end-to-end rehearsal (chaos = detection, recovery =
durability, reshard = capacity, contract = the front door, failover =
replication) — this one pins the MULTIHOST SERVICE PLANE
(``sherman_tpu/multihost.py``):

  phase 1  build TWO emulated host contexts in one process: each host
           its own cluster/tree/engine, its own recovery plane in ONE
           shared directory (chain namespaces ``-h0-`` / ``-h1-``),
           its own front door — behind one ``MultihostService`` whose
           ``HostRouter`` partitions the key space.  Bulk values land
           on their owner host only.
  traffic  open-loop writers + a deleter (exactly-once rids) + readers
           hammer the ROUTED front door: every batch splits by owner,
           each sub-batch is acked by the OWNER's journal only, and
           the merged future reassembles batch order.  A per-host
           delta checkpoint runs mid-stream on BOTH chains.
  crash    both front doors are killed mid-traffic (no drain) and
           host 0's live journal tail is TORN (half a frame appended)
           — host 1's chain stays clean: the drill's core claim is
           that one host's torn tail never blocks the other's replay.
  recover  ``RecoveryPlane.recover_union``: every host's chain is
           restored + replayed independently; the merged acked-op
           ledger (inserts AND deletes, both hosts) is then audited
           against the recovered engines — ``rpo_ops == 0`` and
           ``lost_acks == 0``, plus an untouched-key probe.
  tail     the cross-host replication seam: a follower group attached
           to host 0's recovered plane ships host 0's ``-h0-`` chain
           out of the SHARED directory (host 1's files interleaved
           beside it must be ignored), applies a fresh acked round,
           converges, and serves certified replica reads.  The full
           client history — both hosts, both sides of the crash, plus
           the replica-served reads — checks linearizable offline.
  a/b      journal ack bandwidth: the hosts' concurrent write streams
           through ONE shared journal vs one journal EACH, both under
           the shipped front-door discipline (group commit).  The
           shared stream must coalesce the hosts' acks through the
           bounded-latency commit window; per-host ownership makes
           every stream a lone writer, which skips the window by
           design and acks at raw fsync speed.  Per-host chains must
           clear >= 1.5x aggregate acks/s (the window-less contended
           stream is published too, never gated — on one shared
           device its fsyncs semi-serialize in the filesystem
           journal, an emulation artifact real per-host disks do not
           have).

Runs on the CPU mesh anywhere (``bench.py --multihost-drill`` forwards
here; ``scripts/multihost_ci.sh`` pins it in CI).  Prints ONE JSON
line ``{"metric": "multihost_drill", "ok": true, "rpo_ops": 0,
"lost_acks": 0, "linearizable": true, "ack_bandwidth": {...}, ...}``
and mirrors it to ``SHERMAN_MULTIHOST_RECEIPT`` when set.  perfgate
treats the committed receipt as a robustness artifact: never
throughput-gated against hosts=1 rounds (the ``hosts`` comparability
wall), but ``rpo_ops > 0`` / ``lost_acks > 0`` / ``linearizable ==
false`` is a marginless hard red.  Env knobs: SHERMAN_DRILL_KEYS
(default 4000), SHERMAN_CHAOS_SEED, SHERMAN_DRILL_SECS.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

from common import build_cluster, pages_for_keys, setup_platform

SALT = 0x30057FEB  # bulk-load value stamp (key ^ SALT)


def _chunked_search(eng, keys: np.ndarray, width: int = 512):
    """Engine point reads in dispatch-sized chunks -> (values, found)."""
    vs, fs = [], []
    for i in range(0, keys.size, width):
        v, f = eng.search(keys[i:i + width])
        vs.append(np.asarray(v, np.uint64))
        fs.append(np.asarray(f, bool))
    return np.concatenate(vs), np.concatenate(fs)


def _ack_bandwidth_ab(root: str, n_hosts: int, total: int,
                      gc_ms: float = 2.0) -> dict:
    """The perf claim, measured where it lives: ``n_hosts`` concurrent
    closed-loop write streams (one per host's write lane) acking
    ``total`` durable appends through ONE shared journal vs one
    journal EACH, both under the SHIPPED front-door journal discipline
    (``group_commit_ms`` — the same value this drill's own front doors
    run).  The mechanism being measured is contention: a single
    logical journal must coalesce the hosts' concurrent acks through
    the bounded-latency group-commit window (every group pays up to
    the window in added ack latency), while per-host ownership makes
    every stream a LONE writer — which skips the window entirely by
    design and acks at raw per-op-fsync speed, with the N fsync
    streams running their disk waits in parallel.

    ``shared_percommit_acks_s`` is published alongside, NEVER gated:
    the same contended shared stream with the window forced off
    (``group_commit_ms=0``), where concurrent appends still coalesce
    implicitly (one leader fsync covers the joiners).  On this
    emulation both "hosts" share one device, so cross-file fsyncs
    semi-serialize on the filesystem journal and that pair
    under-measures the stream-parallelism term a real pod's
    independent disks provide — it is reported for completeness, not
    the claim's baseline."""
    from sherman_tpu.utils import journal as J

    def run(journals, n_thr: int) -> tuple[float, int]:
        per_thr = total // n_thr
        barrier = threading.Barrier(n_thr + 1)

        def writer(t: int):
            jr = journals[t % len(journals)]
            k = np.asarray([t + 1], np.uint64)
            v = np.asarray([t + 1], np.uint64)
            barrier.wait()
            for i in range(per_thr):
                jr.append(J.J_UPSERT, k, v, rid=(t << 32) | i)

        ths = [threading.Thread(target=writer, args=(t,), daemon=True)
               for t in range(n_thr)]
        for th in ths:
            th.start()
        barrier.wait()
        t0 = time.perf_counter()
        for th in ths:
            th.join(timeout=600)
        dt = time.perf_counter() - t0
        fsyncs = sum(jr.fsyncs for jr in journals)
        for jr in journals:
            jr.close()
        return (n_thr * per_thr) / max(dt, 1e-9), fsyncs

    shared, sh_fs = run([J.Journal(
        os.path.join(root, "ab-shared.wal"), sync=True,
        group_commit_ms=gc_ms)], n_hosts)
    percommit, _pc_fs = run([J.Journal(
        os.path.join(root, "ab-percommit.wal"), sync=True)], n_hosts)
    perhost, ph_fs = run([J.Journal(
        os.path.join(root, f"ab-h{t}.wal"), sync=True,
        group_commit_ms=gc_ms) for t in range(n_hosts)], n_hosts)
    return {
        "hosts": n_hosts, "acks_total": total,
        "group_commit_ms": gc_ms,
        "shared_acks_s": round(shared, 1),
        "shared_acks_per_fsync": round(total / max(sh_fs, 1), 2),
        "shared_percommit_acks_s": round(percommit, 1),
        "perhost_acks_s": round(perhost, 1),
        "perhost_acks_per_fsync": round(total / max(ph_fs, 1), 2),
        "speedup": round(perhost / max(shared, 1e-9), 3),
        "speedup_vs_percommit": round(
            perhost / max(percommit, 1e-9), 3),
    }


def main(argv=None) -> dict:
    p = argparse.ArgumentParser()
    p.add_argument("--keys", type=int,
                   default=int(os.environ.get("SHERMAN_DRILL_KEYS", 4000)))
    p.add_argument("--hosts", type=int, default=2,
                   help="emulated host count (>= 2)")
    p.add_argument("--seed", type=int,
                   default=int(os.environ.get("SHERMAN_CHAOS_SEED", 7)))
    p.add_argument("--secs", type=float,
                   default=float(os.environ.get("SHERMAN_DRILL_SECS", 2.0)))
    p.add_argument("--dir", default=None,
                   help="drill directory (default: a tempdir)")
    a = p.parse_args(argv)
    assert a.hosts >= 2, "the multihost drill wants >= 2 hosts"
    # one device per emulated host: per-host engines are single-device
    # programs (no collective rendezvous to interleave across the
    # concurrent per-host executors — the failover drill's lesson)
    setup_platform(1)

    from sherman_tpu import audit as A
    from sherman_tpu import obs
    from sherman_tpu.errors import ShermanError
    from sherman_tpu.models import batched
    from sherman_tpu.models.validate import check_structure_device
    from sherman_tpu.multihost import HostRouter, MultihostService
    from sherman_tpu.recovery import RecoveryPlane
    from sherman_tpu.replica import ReplicaGroup
    from sherman_tpu.serve import (RetryingClient, RetryPolicy,
                                   ServeConfig, ShermanServer)
    from sherman_tpu.utils import journal as J

    t_start = time.time()
    H = a.hosts
    out: dict = {"metric": "multihost_drill", "seed": a.seed, "ok": False,
                 "hosts": H, "keys": a.keys}
    root = a.dir or tempfile.mkdtemp(prefix="sherman_multihost_")
    out["dir"] = root
    snap0 = obs.snapshot()

    # -- phase 1: N host contexts, one shared chain directory -----------------
    router = HostRouter(H)
    rng = np.random.default_rng(3)
    keys = np.unique(rng.integers(1, 1 << 56, int(a.keys * 1.05),
                                  dtype=np.uint64))[:a.keys]
    vals = keys ^ np.uint64(SALT)
    own = router.owner(keys)
    out["key_split"] = [int((own == h).sum()) for h in range(H)]
    assert all(n > 0 for n in out["key_split"]), "degenerate key split"

    widths = (256, 1024)
    big = {c: 1e9 for c in ("read", "scan", "insert", "delete")}

    def front_door(engine, host_id: int, calib: np.ndarray):
        cfg = ServeConfig(widths=widths, p99_targets_ms=dict(big),
                          write_linger_ms=0.5, write_width=2048,
                          group_commit_ms=2.0)
        srv = ShermanServer(engine, cfg, host_id=host_id)
        absent = np.asarray([1 << 60], np.uint64)
        # value-preserving calibration against THIS host's owned keys
        ck = calib[:256]
        cv, cf = engine.search(ck)
        srv.start(calib_keys=calib,
                  calib_writes=(ck[cf], np.asarray(cv)[cf]),
                  calib_delete_keys=absent)
        return srv

    ppn = pages_for_keys(a.keys)
    hosts = []  # [(cluster, tree, eng, plane, srv, my_keys)]
    for h in range(H):
        cluster, tree, eng = build_cluster(
            1, ppn, batch_per_node=512,
            locks_per_node=1024, chunk_pages=64)
        my = keys[own == h]
        batched.bulk_load(tree, my, my ^ np.uint64(SALT))
        eng.attach_router()
        check_structure_device(tree)
        plane = RecoveryPlane(cluster, tree, eng, root,
                              group_commit_ms=2.0, host_id=h, hosts=H)
        plane.checkpoint_base()
        srv = front_door(eng, h, my)
        hosts.append((cluster, tree, eng, plane, srv, my))
    svc = MultihostService([hc[4] for hc in hosts], router,
                           planes=[hc[3] for hc in hosts])

    # -- acked mixed traffic through the routed front door --------------------
    # writer slices + a delete slice + an immutable tail; every client
    # batch is random over its slice, so every batch SPLITS across
    # owner hosts (the whole point of the drill)
    n_writers, n_readers = 2, 1
    per = a.keys // (n_writers + 2)
    del_slice = keys[n_writers * per:(n_writers + 1) * per]
    imm = keys[(n_writers + 1) * per:]
    # merged acked-op ledger: key -> (present, value) after the LAST
    # acked op (slices are disjoint per client thread, so per-key
    # order is each thread's program order)
    acked: list[dict] = [dict() for _ in range(n_writers + 1)]
    unacked: list[dict] = [dict() for _ in range(n_writers + 1)]
    events: list[list] = [[] for _ in range(n_writers + 1 + n_readers)]
    stop = threading.Event()
    gens = [0] * n_writers
    pol = RetryPolicy(max_attempts=6, hedge_reads=False)

    def writer(w: int, n_reqs: int):
        my = keys[w * per:(w + 1) * per]
        cl = RetryingClient(svc, tenant=f"writer{w}", policy=pol,
                            seed=100 + w + gens[w])
        ev = events[w]
        wrng = np.random.default_rng(1000 * w + gens[w])
        done = 0
        while not stop.is_set() and (n_reqs == 0 or done < n_reqs):
            gens[w] += 1
            done += 1
            time.sleep(0.005)
            kreq = np.unique(my[wrng.integers(0, my.size, 48)])
            vreq = kreq ^ np.uint64(SALT) ^ np.uint64(gens[w] << 8)
            t_inv = time.perf_counter()
            try:
                ok = cl.insert(kreq, vreq)
            except ShermanError:
                # in flight at the kill: result unknown, not owed
                for k, v in zip(kreq.tolist(), vreq.tolist()):
                    unacked[w].setdefault(k, []).append((True, v))
                continue
            t_resp = time.perf_counter()
            for k, v, o in zip(kreq.tolist(), vreq.tolist(),
                               ok.tolist()):
                if o:
                    acked[w][k] = (True, v)
                    ev.append((k, A.OP_INSERT, t_inv, t_resp, v, True))

    def deleter(n_reqs: int):
        cl = RetryingClient(svc, tenant="deleter", policy=pol,
                            seed=300)
        ev = events[n_writers]
        drng = np.random.default_rng(4000)
        done = 0
        while not stop.is_set() and (n_reqs == 0 or done < n_reqs):
            done += 1
            time.sleep(0.011)
            kreq = np.unique(
                del_slice[drng.integers(0, del_slice.size, 24)])
            t_inv = time.perf_counter()
            try:
                found = cl.delete(kreq)
            except ShermanError:
                for k in kreq.tolist():
                    unacked[n_writers].setdefault(k, []).append(
                        (False, None))
                continue
            t_resp = time.perf_counter()
            for k, f in zip(kreq.tolist(), found.tolist()):
                # an acked delete leaves the key absent whether or not
                # this call found it
                acked[n_writers][k] = (False, None)
                ev.append((k, A.OP_DELETE, t_inv, t_resp, None,
                           bool(f)))

    def reader(r: int):
        cl = RetryingClient(svc, tenant=f"reader{r}", policy=pol,
                            seed=200 + r, deadline_ms=5000.0)
        ev = events[n_writers + 1 + r]
        rrng = np.random.default_rng(50 + r)
        while not stop.is_set():
            kreq = np.unique(keys[rrng.integers(0, keys.size, 64)])
            t_inv = time.perf_counter()
            try:
                got, found = cl.read(kreq)
            except ShermanError:
                continue
            t_resp = time.perf_counter()
            for k, g, f in zip(kreq.tolist(), got.tolist(),
                               found.tolist()):
                ev.append((k, A.OP_READ, t_inv, t_resp,
                           g if f else None, bool(f)))
            time.sleep(0.001)

    readers = [threading.Thread(target=reader, args=(r,), daemon=True)
               for r in range(n_readers)]
    for t in readers:
        t.start()
    n_round = max(4, int(a.secs * 5))

    def run_round(n_reqs: int):
        ws = [threading.Thread(target=writer, args=(w, n_reqs),
                               daemon=True) for w in range(n_writers)]
        ws.append(threading.Thread(target=deleter, args=(n_reqs,),
                                   daemon=True))
        for t in ws:
            t.start()
        return ws

    # round 1: acked load on the base chains
    for t in run_round(n_round):
        t.join(timeout=300)

    # per-host delta checkpoints mid-stream: BOTH chains grow a link
    # (rotation + sweep each scoped to its own -h<i>- namespace)
    deltas = [hc[3].checkpoint_delta() for hc in hosts]
    out["delta_pages"] = [int(d["pages"]) for d in deltas]

    # round 2: acked load on the fresh segments
    for t in run_round(n_round):
        t.join(timeout=300)

    # round 3: open-ended — the in-flight-at-the-kill load
    ws = run_round(0)
    time.sleep(min(0.5, a.secs / 4))

    # -- crash: kill both doors, tear host 0's tail ONLY ----------------------
    svc_stats = svc.stats()
    for hc in hosts:
        hc[4].kill()
    stop.set()
    for t in ws + readers:
        t.join(timeout=120)
    frontiers = svc.journal_frontiers()
    out["frontiers"] = [[os.path.basename(p), int(n)]
                        for p, n in frontiers]
    torn_path = hosts[0][2].journal.path
    with open(torn_path, "ab") as f:  # crash mid-append: torn half-frame
        rec = J.encode_record(J.J_UPSERT,
                              np.asarray([1 << 40], np.uint64),
                              np.asarray([7], np.uint64), rid=0xDEAD)
        f.write(rec[: len(rec) // 2])
    out["torn"] = os.path.basename(torn_path)
    assert "-h0-" in out["torn"], "tore the wrong host's tail"

    # -- union recovery: every chain independently, torn tail local -----------
    ctxs, union = RecoveryPlane.recover_union(root, hosts=H)
    out["union"] = {"chains": union["chains"],
                    "replay": union["replay"],
                    "per_host_ms": union["per_host_ms"],
                    "total_ms": union["total_ms"]}

    # -- RPO: the merged acked-op ledger against the recovered engines --------
    merged: dict = {}
    for d in acked:
        merged.update(d)
    assert merged, "drill acked no ops before the kill"
    assert any(not pres for pres, _ in merged.values()), \
        "drill acked no deletes (mixed traffic pin)"
    ak = np.asarray(sorted(merged), np.uint64)
    a_pres = np.asarray([merged[int(k)][0] for k in ak], bool)
    a_val = np.asarray([merged[int(k)][1] or 0 for k in ak], np.uint64)
    a_own = router.owner(ak)
    rpo = 0
    post_events = []
    for h in range(H):
        sel = a_own == h
        if not sel.any():
            continue
        t_inv = time.perf_counter()
        got, found = _chunked_search(ctxs[h][3], ak[sel])
        t_resp = time.perf_counter()
        rpo += int((found != a_pres[sel]).sum())
        rpo += int((got[found & a_pres[sel]]
                    != a_val[sel][found & a_pres[sel]]).sum())
        post_events += [(int(k), A.OP_READ, t_inv, t_resp,
                         int(g) if f else None, bool(f))
                        for k, g, f in zip(ak[sel].tolist(),
                                           got.tolist(),
                                           found.tolist())]
    out["rpo_ops"] = rpo
    assert rpo == 0, f"{rpo} acked ops lost across union recovery"
    # untouched-key probe: bulk values still served verbatim
    lost = rpo
    probe = keys[~np.isin(keys, ak)][:: max(1, a.keys // 512)]
    p_own = router.owner(probe)
    for h in range(H):
        pk = probe[p_own == h]
        if not pk.size:
            continue
        got, found = _chunked_search(ctxs[h][3], pk)
        lost += int((~found).sum()) + int(
            (got[found] != (pk ^ np.uint64(SALT))[found]).sum())
    out["lost_acks"] = lost
    assert lost == 0, f"{lost} acked/bulk ops lost across recovery"

    # -- cross-host replication seam: tail -h0- out of the shared dir ---------
    # the follower group attaches to host 0's recovered plane; its
    # tailer ships the -h0- chain while host 1's base/delta/journal
    # files sit interleaved in the SAME directory — picking up any of
    # them would corrupt the bootstrap, so convergence IS the pin.
    plane0, cl0, tree0, eng0, _r0 = ctxs[0]
    group = ReplicaGroup(plane0, 1, cache_slots=4096)
    h0keys = keys[own == 0]
    srv0 = front_door(eng0, 0, h0keys)
    tail_acked: dict = {}
    wcl = RetryingClient(srv0, tenant="tailwriter", policy=pol,
                         seed=900)
    wrng = np.random.default_rng(42)
    for _ in range(max(4, n_round // 2)):
        kreq = np.unique(h0keys[wrng.integers(0, h0keys.size, 48)])
        vreq = kreq ^ np.uint64(SALT) ^ np.uint64(0x9999 << 16)
        t_inv = time.perf_counter()
        ok = wcl.insert(kreq, vreq)
        t_resp = time.perf_counter()
        for k, v, o in zip(kreq.tolist(), vreq.tolist(), ok.tolist()):
            if o:
                tail_acked[k] = v
                post_events.append((k, A.OP_INSERT, t_inv, t_resp, v,
                                    True))
    lag_ms = group.measure_lag()
    fol = group.followers[0]
    tk = np.asarray(sorted(tail_acked), np.uint64)
    tv = np.asarray([tail_acked[int(k)] for k in tk], np.uint64)
    got, found = _chunked_search(fol.eng, tk)
    diverged = int((~found).sum()) + int((got[found] != tv[found]).sum())
    assert diverged == 0, \
        f"cross-host follower diverged on {diverged} acked keys"
    # certified replica reads over host 0's immutable slice
    imm0 = imm[router.owner(imm) == 0]
    fol.admit(imm0)
    t_inv = time.perf_counter()
    got, found = group.read(imm0[:256])
    t_resp = time.perf_counter()
    post_events += [(int(k), A.OP_READ, t_inv, t_resp,
                     int(g) if f else None, bool(f))
                    for k, g, f in zip(imm0[:256].tolist(),
                                       np.asarray(got).tolist(),
                                       np.asarray(found).tolist())]
    st = group.stats()
    out["tail"] = {
        "of_host": 0, "applied_records": st["applied_records"],
        "applied_rows": st["applied_rows"], "lag_ms": round(lag_ms, 2),
        "reads_served": st["reads_served"],
        "reads_forwarded": st["reads_forwarded"],
        "converged_keys": int(tk.size),
    }
    assert st["applied_records"] > 0, "the cross-host tail shipped nothing"
    assert st["reads_served"] > 0, "no replica-served reads"
    srv0.drain()
    group.close()

    # -- offline linearizability over the WHOLE routed history ----------------
    all_events = [e for ev in events for e in ev] + post_events
    initial = {int(k): (True, int(v)) for k, v in zip(keys, vals)}
    open_w: dict = {}
    for d in unacked:
        for k, outs in d.items():
            open_w.setdefault(k, []).extend(outs)
    verdict = A.check_events(all_events, initial=initial,
                             open_writes=open_w)
    out["audit"] = {
        "events": verdict["events"],
        "keys": verdict["keys"],
        "reads_checked": verdict["reads"],
        "violations": len(verdict["violations"]),
        "linearizable": bool(verdict["linearizable"]),
    }
    out["linearizable"] = bool(verdict["linearizable"])
    if verdict["violations"]:
        out["audit"]["first_violations"] = verdict["violations"][:3]
    assert verdict["linearizable"], \
        f"history not linearizable: {verdict['violations'][:3]}"
    assert verdict["reads"] > 0, "audit checked no reads"
    jsonl = os.path.join(root, "history.jsonl")
    A.dump_jsonl(all_events, jsonl)
    out["history_jsonl"] = jsonl

    # -- the service-plane receipt --------------------------------------------
    out["service"] = {
        "admitted_ops": svc_stats["admitted_ops"],
        "served_ops": svc_stats["served_ops"],
        "acked_writes": svc_stats["acked_writes"],
        "widths": svc_stats["widths"],
        "contract": svc_stats["contract"],
    }
    if "journal" in svc_stats:
        out["service"]["journal"] = svc_stats["journal"]
    assert svc_stats["acked_writes"] > 0

    # -- journal ack bandwidth: shared stream vs per-host streams -------------
    out["ack_bandwidth"] = _ack_bandwidth_ab(root, n_hosts=H,
                                             total=1000)
    assert out["ack_bandwidth"]["speedup"] >= 1.5, (
        "per-host journal streams cleared only "
        f"{out['ack_bandwidth']['speedup']}x the shared stream "
        "(want >= 1.5x)")

    for _pl, _cl, _tr, _en, _rc in ctxs:
        _pl.close()
    d = obs.delta(snap0, obs.snapshot())
    out["obs"] = {k: round(float(d[k]), 2) for k in sorted(d)
                  if k.startswith("multihost.")
                  or k in ("recovery.replayed_records",)}
    assert d.get("multihost.split_submits", 0) > 0
    out["elapsed_s"] = round(time.time() - t_start, 1)
    out["ok"] = True
    line = json.dumps(out)
    print(line)
    receipt = os.environ.get("SHERMAN_MULTIHOST_RECEIPT")
    if receipt:
        with open(receipt, "w") as f:
            f.write(line + "\n")
    print("MULTIHOST-DRILL PASS", file=sys.stderr)
    return out


if __name__ == "__main__":
    main()
