#!/usr/bin/env python
"""White-box device-telemetry report: compile ledger + roofline receipts.

The SLO plane answers "how fast was it"; this driver answers "how close
to the machine was it, and did anything recompile behind our back".
Two modes:

- ``--receipt BENCH.json``: render the ``device`` section of a schema-3
  bench receipt (bare or driver-wrapped ``{"parsed": {...}}``) as the
  side-by-side tables — the chip workflow: run ``bench.py`` on the TPU,
  commit the JSON, read the receipts anywhere without a device.
- live (default): build a small tree, run the staged read-only loop
  under a SEALED compile ledger — the zero-retrace steady-state pin:
  warmup covers both carry variants, so ANY compile inside the sealed
  window is a silent retrace and the report raises — then attribute
  per-phase walls (chained-delta, ``step.phase_profile``) and join them
  with each compiled program's ``cost_analysis()`` byte/flop floor into
  roofline receipts (:func:`sherman_tpu.obs.device.rooflines`).

Env knobs (live mode): KEYS (20 K), B (8192), DEVB (B), K (delta reps,
2), STEPS (sealed steps, 8), FUSION (config.staged_fusion), SAMPLER
(analytic), THETA (0.99).  ``SHERMAN_PEAK_GBPS``/``SHERMAN_PEAK_TFLOPS``
set the roofs on devices the peak table does not know (absolute
achieved rates print otherwise — fractions are never invented).
``SHERMAN_BENCH_DEVICE_MEMORY=0`` skips per-program memory_analysis.
``SHERMAN_LEAF_CACHE`` runs the sealed loop with the hot-key tier's
``cache_probe`` program chained in (prefilled with the hottest ranks)
— the zero-retrace pin then covers the cache-on serving loop.

Output (the profile_gather/profile_staged2 conventions): the ledger
table (program, compiles, compile ms, retraces), the roofline table
(phase, program, wall ms, GB/s, GF/s, fraction-of-peak, bound), the
memory gauges, and ONE JSON line ``{"metric": "device_report", ...}``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _f(v, w=8, p=2):
    """Right-aligned number or an em-dash for absent values."""
    return f"{v:{w}.{p}f}" if isinstance(v, (int, float)) else f"{'—':>{w}s}"


def print_tables(dev: dict, file=sys.stderr) -> None:
    """The side-by-side tables of one ``device`` section (bench JSON
    schema 3 or the live report's identical shape)."""
    led = dev.get("ledger") or {}
    print(f"# compile ledger ({dev.get('compile_source', '?')}): "
          f"{led.get('programs', 0)} programs, "
          f"{led.get('compiles', 0)} compiles, "
          f"{led.get('compile_ms_total', 0)} ms total, "
          f"{led.get('retraces', 0)} steady-state retraces over "
          f"{led.get('sealed_windows', 0)} sealed windows", file=file)
    print(f"# {'program':34s} {'compiles':>8s} {'compile ms':>11s} "
          f"{'retraces':>8s}", file=file)
    for e in led.get("entries", ()):
        print(f"# {e['label']:34s} {e['compiles']:>8d} "
              f"{e['compile_ms']:>11.1f} {e['retraces']:>8d}", file=file)
    peaks = dev.get("peaks") or {}
    for group, phases in (dev.get("rooflines") or {}).items():
        print(f"#\n# roofline receipts [{group}] "
              f"(peaks: {peaks.get('source', '?')})", file=file)
        print(f"# {'phase':22s} {'program':30s} {'wall ms':>8s} "
              f"{'GB/s':>8s} {'GF/s':>8s} {'B-frac':>8s} {'F-frac':>8s} "
              f"{'bound':>6s}", file=file)
        for ph, rec in phases.items():
            if not rec.get("available"):
                print(f"# {ph:22s} {rec.get('program', '?'):30s} "
                      f"{_f(rec.get('wall_ms'))} unavailable: "
                      f"{rec.get('reason', '?')}", file=file)
                continue
            if rec.get("wall_below_resolution"):
                # a sub-resolution wall makes the achieved rates noise
                # (532 TB/s "bandwidth" on a 0.00 ms wall) — the JSON
                # keeps them; the human table must not present them
                print(f"# {ph:22s} {rec.get('program', '?'):30s} "
                      f"{_f(rec.get('wall_ms'))} {'<res':>8s} {'<res':>8s} "
                      f"{'—':>8s} {'—':>8s} {'—':>6s}", file=file)
                continue
            print(f"# {ph:22s} {rec.get('program', '?'):30s} "
                  f"{_f(rec.get('wall_ms'))} "
                  f"{_f(rec.get('achieved_gbytes_s'))} "
                  f"{_f(rec.get('achieved_gflops_s'))} "
                  f"{_f(rec.get('achieved_bytes_frac'), p=4)} "
                  f"{_f(rec.get('achieved_flops_frac'), p=4)} "
                  f"{rec.get('bound', '—'):>6s}", file=file)
    mem = dev.get("memory") or {}
    if mem:
        print("#\n# memory gauges: "
              + ", ".join(f"{k} {v}" for k, v in sorted(mem.items())),
              file=file)


def _receipt_report(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    dev = doc.get("device") if isinstance(doc, dict) else None
    if not isinstance(dev, dict):
        out = {"metric": "device_report", "source": path,
               "error": "no device section (schema_version < 3 or "
                        "SHERMAN_DEVICE_OBS=0 run)"}
        print(json.dumps(out))
        return out
    print_tables(dev)
    out = {"metric": "device_report", "source": path,
           "schema_version": doc.get("schema_version"),
           "retraces": (dev.get("ledger") or {}).get("retraces"),
           "device": dev}
    print(json.dumps(out))
    return out


def _live_report() -> dict:
    import jax

    jax.config.update("jax_compilation_cache_dir", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache"))

    import common
    from sherman_tpu import native, obs
    from sherman_tpu import config as C
    from sherman_tpu.config import LEAF_CAP
    from sherman_tpu.models import batched
    from sherman_tpu.obs import device as DEV
    from sherman_tpu.ops import bits
    from sherman_tpu.workload import device_prep

    n_keys = int(os.environ.get("KEYS", 20_000))
    batch = int(os.environ.get("B", 8192))
    dev_b = int(os.environ.get("DEVB", batch))
    theta = float(os.environ.get("THETA", 0.99))
    K = int(os.environ.get("K", 2))
    S = int(os.environ.get("STEPS", 8))
    fusion = os.environ.get("FUSION") or C.staged_fusion()
    sampler = os.environ.get("SAMPLER", "analytic")
    salt = 0x5E17_AB1E_5A17
    per_leaf = max(1, int(LEAF_CAP * 0.75))
    est_pages = int(n_keys / per_leaf * 1.10) + 2048
    pages = 1 << max(12, (est_pages - 1).bit_length())

    # a fresh ledger for THIS report: the process may have compiled
    # under other labels before (pytest smoke); the programs built
    # below are new jit objects, so their compiles land cleanly
    ledger = DEV.get_ledger()
    ledger.reset()

    _, tree, eng = common.build_cluster(1, pages, batch)
    if native.available():
        keys, _ = native.synthetic_keyspace(n_keys, salt)
    else:
        ranks = np.arange(n_keys, dtype=np.uint64)
        keys = np.sort(bits.mix64_np(ranks ^ np.uint64(salt)))
    t0 = time.time()
    with obs.span("device_report.bulk_load", keys=n_keys):
        batched.bulk_load(tree, keys, keys ^ np.uint64(0xDEADBEEF),
                          fill=0.75)
    eng.attach_router()
    print(f"# bulk_load {time.time() - t0:.1f}s", file=sys.stderr)

    # hot-key tier (SHERMAN_LEAF_CACHE): run the sealed loop with the
    # cache_probe program chained in — the zero-retrace pin then covers
    # the cache-on serving loop (fixed table shapes by construction)
    lc = None
    if C.leaf_cache_slots():
        lc = eng.attach_leaf_cache()
        hot = bits.mix64_np(
            np.arange(min(lc.capacity, n_keys), dtype=np.uint64)
            ^ np.uint64(salt))
        filled = lc.fill(hot)
        print(f"# leaf cache: {lc.slots} slots, prefilled "
              f"{filled['placed']} hottest ranks", file=sys.stderr)
    step, (new_carry, tb, rt, rk) = device_prep.make_staged_step(
        eng, n_keys=n_keys, theta=theta, salt=salt, batch=batch,
        dev_b=dev_b, sampler=sampler, fusion=fusion, leaf_cache=lc)
    dsm = eng.dsm
    pool, counters = dsm.pool, dsm.counters

    # warmup: BOTH carry variants (fresh new_carry() host shardings and
    # the threaded program outputs are distinct jit entries), so the
    # sealed window below must observe zero compiles
    carry = new_carry()
    counters, carry = step(pool, counters, tb, rt, rk, carry)
    counters, carry = step(pool, counters, tb, rt, rk, carry)
    carry = step.drain(carry)
    jax.block_until_ready(carry)

    # sealed steady-state loop — the zero-retrace pin
    with ledger.sealed_scope():
        t0 = time.perf_counter()
        for _ in range(S):
            counters, carry = step(pool, counters, tb, rt, rk, carry)
        carry = step.drain(carry)
        jax.block_until_ready(carry)
        wall = time.perf_counter() - t0
    assert int(np.asarray(carry[1])) == 1, "unique overflow"
    assert int(np.asarray(carry[2])) == (S + 2) * batch, \
        "staged receipts failed"
    retraces = ledger.retraces
    cache_hit_ratio = None
    if lc is not None:
        hits = int(np.asarray(carry[5]))
        cache_hit_ratio = hits / ((S + 2) * batch)
        print(f"# leaf cache: {hits} client hits "
              f"(ratio {cache_hit_ratio:.4f})", file=sys.stderr)
        assert hits > 0, "cache-on sealed loop served zero hits"
    print(f"# sealed loop: {S} steps in {wall:.3f}s "
          f"({wall / S * 1e3:.2f} ms/step), {retraces} retraces",
          file=sys.stderr)

    with obs.span("device_report.phase_attribution", reps=K):
        phase_ms, counters = step.phase_profile(pool, counters, tb, rt,
                                                rk, reps=K)
    device_prep.record_phase_obs("staged", phase_ms)
    dsm.counters = counters

    peaks = DEV.device_peaks()
    want_mem = os.environ.get("SHERMAN_BENCH_DEVICE_MEMORY", "1") != "0"
    roofs = DEV.rooflines(phase_ms, step.phase_labels, memory=want_mem,
                          peaks=peaks, ledger=ledger)
    dev = {
        "compile_source": ledger.attach(),
        "ledger": ledger.summary(),
        "peaks": peaks,
        "rooflines": {"staged": roofs},
        "memory": DEV.get_accountant().gauges(),
    }
    print_tables(dev)
    out = {"metric": "device_report", "fusion": step.fusion,
           "keys": n_keys, "batch": batch, "steps": S,
           "wall_ms_per_step": round(wall / S * 1e3, 3),
           "retraces": retraces,
           "cache": ({"slots": lc.slots,
                      "hit_ratio": round(cache_hit_ratio, 4)}
                     if lc is not None else None),
           "device": dev}
    print(json.dumps(out))
    # the pin itself: a live report with a steady-state retrace is a
    # broken serving loop, not a report
    assert retraces == 0, \
        f"{retraces} steady-state retraces in the sealed loop (see " \
        "the compile ledger table above)"
    return out


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(
        description="white-box device report: compile ledger + rooflines")
    ap.add_argument("--receipt", default=None,
                    help="render a schema-3 bench JSON's device section "
                         "instead of running the live sealed loop")
    a = ap.parse_args(argv)
    if a.receipt:
        return _receipt_report(a.receipt)
    return _live_report()


if __name__ == "__main__":
    main()
