#!/usr/bin/env python
"""Partition drill: replication chaos, quorum acks, split-brain
fencing and anti-entropy follower repair, rehearsed end to end.

The sixth end-to-end rehearsal (chaos = detection, recovery =
durability, reshard = capacity, contract = the front door, failover =
replication) — this one pins the PARTITION plane:

  phase 1  build + bulk-load a 1-node CPU mesh, arm the recovery
           plane, attach a ReplicaGroup of R journal-shipped
           followers and a seeded replication fault layer
           (``chaos.ReplChaos`` out of the same ``FaultPlan``
           grammar): scheduled drop/delay/reorder/slow windows at the
           journal-shipping tail fire DURING the quorum rounds below.
  phase A  primary-only acks (``ack_quorum=1``, the shipped default):
           exactly-once write rounds + a concurrent reader build the
           client ledger; per-round ack latency is the quorum
           comparison's baseline.
  quorum   a front door with ``ack_quorum=2`` gates every write ack
           on one follower's durable watermark COVERING the ack's
           journal frontier — same rounds, same ledger, the latency
           delta published.  Then a manual ship partition
           (``chaos.hold``): the quorum wait expires BOUNDED and
           typed (``QuorumTimeoutError``); after the heal the SAME
           rid retried re-acks the ORIGINAL result through the dedup
           window (``fut.deduped`` — exactly-once across quorum
           retries, never a second apply).
  repair   one follower's pool is corrupted by hand; an anti-entropy
           tick (full page compare) DETECTS the divergence,
           quarantines the follower out of the read-serving set,
           re-ships it through the restore-then-replay core and
           re-admits it clean — ``diverged_followers_unrepaired ==
           0``, the re-join catch-up published.
  fence    split-brain: a lease-scope partition freezes the
           primary's own view of the lease table, the group promotes
           on the majority side (the fence point: epoch bump + the
           durable frontier captured atomically), and the STALE
           primary keeps acking writes it can no longer own — every
           one lands PAST the fence point and never ships.  The heal
           fires the fence: the stale primary's next write fails
           typed (``StalePrimaryError``).
  resume   a fresh front door on the promoted winner adopts the
           replayed exactly-once window; the fenced suffix is counted
           (``count_fenced_suffix`` > 0) and PROVABLY REJECTED —
           ``audit.check_fenced_rejected`` pins ``fenced_acks_merged
           == 0`` against the promoted state — then the client
           re-drives the fenced writes through the new primary's
           dedup window with fresh rids (the contract: typed
           rejection, then re-drive; never a silent merge).
  audit    every pre-fence ack served by the promoted primary
           (``lost_acks == 0``), pre-fence rids retried re-ack not
           re-apply (``duplicate_acks == 0``), and the merged client
           history checks linearizable offline.

Runs on the CPU mesh anywhere (``bench.py --partition-drill``
forwards here; ``scripts/partition_ci.sh`` pins it in CI).  Prints
ONE JSON line ``{"metric": "partition_drill", "ok": true,
"lost_acks": 0, "duplicate_acks": 0, "linearizable": true,
"fenced_acks_merged": 0, ...}`` and mirrors it to
``SHERMAN_PARTITION_RECEIPT`` when set.  perfgate treats the
committed receipt as a robustness artifact: never throughput-gated,
and quorum-ack receipts never gate against primary-only rounds in
EITHER direction; ``fenced_acks_merged > 0`` /
``diverged_followers_unrepaired > 0`` (and the contract pins) are
marginless hard reds.  Env knobs: SHERMAN_DRILL_KEYS (default 3000),
SHERMAN_DRILL_NODES (default 1), SHERMAN_REPL (follower count,
default 2 here), SHERMAN_CHAOS_SEED.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

from common import build_cluster, pages_for_keys, setup_platform

SALT = 0xFA110FEB      # bulk-load value stamp (key ^ SALT)
FENCE_STAMP = 0x0F3A   # the fenced writes' value generation
ROUND_KEYS = 48        # keys per write round


def _median_ms(samples: list) -> float:
    return round(float(np.median(np.asarray(samples))) * 1e3, 3) \
        if samples else 0.0


def _unwrap(e, cls):
    """Walk the cause chain for a typed error (lanes may wrap)."""
    tip = e
    while tip is not None:
        if isinstance(tip, cls):
            return tip
        tip = tip.__cause__
    return None


def main(argv=None) -> dict:
    p = argparse.ArgumentParser()
    p.add_argument("--keys", type=int,
                   default=int(os.environ.get("SHERMAN_DRILL_KEYS",
                                              3000)))
    # default 1 node: same rationale as the failover drill — the
    # drill runs concurrent executors (serve loop + follower pumps)
    # and XLA's CPU collective rendezvous can deadlock across
    # concurrent multi-device launches; chip meshes pass --nodes
    p.add_argument("--nodes", type=int,
                   default=int(os.environ.get("SHERMAN_DRILL_NODES",
                                              1)))
    p.add_argument("--replicas", type=int,
                   default=int(os.environ.get("SHERMAN_REPL", 0) or 2))
    p.add_argument("--seed", type=int,
                   default=int(os.environ.get("SHERMAN_CHAOS_SEED",
                                              11)))
    p.add_argument("--rounds", type=int, default=20,
                   help="write rounds per latency phase")
    p.add_argument("--dir", default=None,
                   help="drill directory (default: a tempdir)")
    a = p.parse_args(argv)
    setup_platform(a.nodes)

    import jax

    from sherman_tpu import audit as A
    from sherman_tpu import obs
    from sherman_tpu.chaos import FaultPlan
    from sherman_tpu.errors import ShermanError
    from sherman_tpu.models import batched
    from sherman_tpu.recovery import RecoveryPlane
    from sherman_tpu.replica import (AntiEntropy, QuorumTimeoutError,
                                     ReplicaGroup, StalePrimaryError)
    from sherman_tpu.serve import (RetryingClient, RetryPolicy,
                                   ServeConfig, ShermanServer)

    t_start = time.time()
    out: dict = {"metric": "partition_drill", "seed": a.seed,
                 "ok": False, "nodes": a.nodes,
                 "replicas": a.replicas}
    root = a.dir or tempfile.mkdtemp(prefix="sherman_partition_")
    out["dir"] = root

    # -- phase 1: primary + replica group + replication fault layer ----------
    ppn = pages_for_keys(a.keys)
    cluster, tree, eng = build_cluster(
        a.nodes, ppn, batch_per_node=512,
        locks_per_node=1024, chunk_pages=64)
    rng = np.random.default_rng(3)
    keys = np.unique(rng.integers(1, 1 << 56, int(a.keys * 1.05),
                                  dtype=np.uint64))[:a.keys]
    vals = keys ^ np.uint64(SALT)
    batched.bulk_load(tree, keys, vals)
    eng.attach_router()
    plane = RecoveryPlane(cluster, tree, eng,
                          os.path.join(root, "primary"),
                          group_commit_ms=2.0)
    plane.checkpoint_base()
    group = ReplicaGroup(plane, a.replicas, cache_slots=2048)

    # the fault layer rides the SAME FaultPlan grammar the data-plane
    # chaos uses; the ship-side windows below fire during the quorum
    # rounds (the pump-while-waiting loop ticks replication time fast,
    # so the windows are wide)
    plan = FaultPlan([
        {"kind": "repl_drop", "poll": 2, "span": 3},
        {"kind": "repl_delay", "poll": 8, "span": 2, "follower": 0},
        {"kind": "repl_reorder", "poll": 12, "span": 6},
        {"kind": "repl_slow", "poll": 20, "span": 2, "ms": 2.0},
    ], seed=a.seed)
    chaos = plan.repl_layer()
    group.attach_chaos(chaos)

    widths = (256 * a.nodes, 1024 * a.nodes)
    big = {c: 1e9 for c in ("read", "scan", "insert", "delete")}

    def front_door(engine, *, ack_quorum=1, with_group=False):
        cfg = ServeConfig(widths=widths, p99_targets_ms=dict(big),
                          write_linger_ms=0.5, write_width=2048,
                          group_commit_ms=2.0, ack_quorum=ack_quorum,
                          quorum_timeout_ms=1500.0)
        srv = ShermanServer(engine, cfg)
        if with_group:
            srv.attach_replica_group(group)
        absent = np.asarray([1 << 60], np.uint64)
        # value-preserving calibration (see failover_drill)
        ck = keys[:256]
        cv, cf = engine.search(ck)
        srv.start(calib_keys=keys,
                  calib_writes=(ck[cf], np.asarray(cv)[cf]),
                  calib_delete_keys=absent)
        return srv

    # reserved keyspace slices: writers never collide, the fenced
    # slice is untouched before the split-brain phase (so a fenced
    # value visible later is provably a merge, never an old write)
    per = a.keys // 6
    w_slices = [keys[0:per], keys[per:2 * per]]       # phase A
    q_slice = keys[2 * per:3 * per]                   # quorum rounds
    f_slice = keys[3 * per:3 * per + 16]              # fenced writes
    untouched = keys[4 * per:]

    acked: dict = {}                 # key -> last acked value (owed)
    rid_ledger: dict = {}            # rid -> (tenant, kreq, vreq, ok)
    events: list = []
    ev_lock = threading.Lock()

    def write_rounds(srv, tenant: str, my: np.ndarray, rounds: int,
                     gen0: int) -> list:
        """Paced exactly-once write rounds; returns per-round ack
        latency seconds (the quorum comparison's raw samples)."""
        cl = RetryingClient(srv, tenant=tenant,
                            policy=RetryPolicy(max_attempts=6),
                            seed=gen0)
        wrng = np.random.default_rng(gen0)
        lat = []
        for g in range(rounds):
            kreq = np.unique(my[wrng.integers(0, my.size,
                                              ROUND_KEYS)])
            vreq = kreq ^ np.uint64(SALT) ^ np.uint64((gen0 + g) << 8)
            rid = cl.next_rid()
            t_inv = time.perf_counter()
            ok = cl.insert(kreq, vreq, rid=rid)
            t_resp = time.perf_counter()
            lat.append(t_resp - t_inv)
            rid_ledger[rid] = (tenant, kreq, vreq, np.array(ok))
            with ev_lock:
                for k, v, o in zip(kreq.tolist(), vreq.tolist(),
                                   ok.tolist()):
                    if o:
                        acked[k] = v
                        events.append((k, A.OP_INSERT, t_inv,
                                       t_resp, v, True))
            group.pump()
        return lat

    # -- phase A: primary-only acks (the latency baseline) -------------------
    srv1 = front_door(eng)
    stop = threading.Event()

    def reader():
        cl = RetryingClient(srv1, tenant="reader",
                            policy=RetryPolicy(max_attempts=4),
                            seed=200, deadline_ms=5000.0)
        rrng = np.random.default_rng(50)
        pool = np.concatenate([w_slices[0], w_slices[1], untouched])
        while not stop.is_set():
            kreq = np.unique(pool[rrng.integers(0, pool.size, 64)])
            t_inv = time.perf_counter()
            try:
                got, found = cl.read(kreq)
            except ShermanError:
                continue
            t_resp = time.perf_counter()
            with ev_lock:
                for k, g, f in zip(kreq.tolist(), got.tolist(),
                                   found.tolist()):
                    events.append((k, A.OP_READ, t_inv, t_resp,
                                   g if f else None, bool(f)))
            time.sleep(0.002)

    rd = threading.Thread(target=reader, daemon=True)
    rd.start()
    lat_base = []
    for w, my in enumerate(w_slices):
        lat_base += write_rounds(srv1, f"writer{w}", my, a.rounds,
                                 gen0=1 + 100 * w)
    stop.set()
    rd.join(timeout=60)
    srv1.drain()

    # -- quorum phase: ack_quorum=2 over the same ledger ---------------------
    srv_q = front_door(eng, ack_quorum=2, with_group=True)
    lat_q = write_rounds(srv_q, "writerq", q_slice, a.rounds,
                         gen0=500)
    st = group.stats()
    assert st["quorum_acks"] > 0, "no write waited on a quorum"
    out["quorum_latency"] = {
        "p50_ms_primary_only": _median_ms(lat_base),
        "p50_ms_quorum": _median_ms(lat_q),
        "delta_ms": round(_median_ms(lat_q) - _median_ms(lat_base),
                          3),
    }

    # bounded wait: a full ship partition expires the quorum TYPED;
    # the heal + same-rid retry re-acks the original result through
    # the dedup window (exactly-once across quorum retries)
    chaos.hold("ship")
    kq = q_slice[:8]
    vq = kq ^ np.uint64(SALT) ^ np.uint64(0x9999 << 8)
    rid_q = (0x51 << 32) | 0x0001
    t0 = time.perf_counter()
    try:
        srv_q.submit("insert", kq, vq, tenant="writerq",
                     rid=rid_q).result(timeout=60)
        raise AssertionError("quorum wait under a full partition "
                             "never expired")
    except ShermanError as e:
        assert _unwrap(e, QuorumTimeoutError) is not None, \
            f"quorum expiry raised untyped {type(e).__name__}: {e}"
    waited_s = time.perf_counter() - t0
    assert waited_s < 10.0, "quorum wait was not bounded"
    out["quorum_timeout"] = {"typed": True,
                             "waited_ms": round(waited_s * 1e3, 1)}
    chaos.heal()
    fut = srv_q.submit("insert", kq, vq, tenant="writerq", rid=rid_q)
    ok_r = fut.result(timeout=60)
    assert fut.deduped, "quorum retry re-applied instead of re-acking"
    out["quorum_retry_deduped"] = True
    with ev_lock:
        t_now = time.perf_counter()
        for k, v, o in zip(kq.tolist(), vq.tolist(),
                           np.asarray(ok_r).tolist()):
            if o:
                acked[k] = v
                events.append((k, A.OP_INSERT, t0, t_now, v, True))
    group.pump()
    srv_q.drain()

    # -- repair phase: detect -> quarantine -> re-ship -> re-admit -----------
    victim = group.followers[-1]
    fdsm = victim.cluster.dsm
    fdsm.pool = jax.device_put(
        fdsm.pool.at[3, 5].set(np.int32(0x7EA5)), fdsm.shard)
    ae = AntiEntropy(group, period_s=0, sample_rows=0, seed=a.seed)
    rc = ae.tick()
    out["anti_entropy"] = {
        "audits": ae.audits,
        "divergences": ae.divergences,
        "repairs": ae.repairs,
        "rejoin_catchup_ms": ae.last_repair_ms,
        "unrepaired": ae.unrepaired(),
        "round": rc,
    }
    assert ae.divergences >= 1, \
        "anti-entropy missed an injected follower divergence"
    assert ae.repairs >= 1 and ae.unrepaired() == 0, \
        "a diverged follower was not repaired and re-admitted"

    # -- fence phase: split-brain under a lease-scope partition --------------
    srv3 = front_door(eng)
    chaos.hold("lease")
    # one write UNDER the cut, BEFORE the promotion: the fence check
    # routes through the frozen lease view, snapshotting the pre-bump
    # table — from here the stale primary cannot watch its own epoch
    k0 = f_slice[:4]
    v0 = k0 ^ np.uint64(SALT) ^ np.uint64(1 << 8)
    t_inv = time.perf_counter()
    ok0 = srv3.submit("insert", k0, v0,
                      tenant="stale", rid=(0xFE << 32) | 1
                      ).result(timeout=60)
    t_resp = time.perf_counter()
    with ev_lock:
        for k, v, o in zip(k0.tolist(), v0.tolist(),
                           np.asarray(ok0).tolist()):
            if o:
                acked[k] = v   # pre-fence: ships, owed
                events.append((k, A.OP_INSERT, t_inv, t_resp, v,
                               True))
    t_part = time.perf_counter()
    rcpt = group.promote(t_dead=t_part)
    out["promote"] = rcpt
    assert rcpt["fence"] is not None, "promotion captured no fence"

    # the stale primary keeps acking: every write below lands PAST
    # the fence point, is never shipped, and must never merge
    fenced_pairs = []
    for j in range(4):
        kf = f_slice[4 + 3 * j: 7 + 3 * j]
        vf = kf ^ np.uint64(SALT) ^ np.uint64(FENCE_STAMP << 8)
        okf = srv3.submit("insert", kf, vf, tenant="stale",
                          rid=(0xFE << 32) | (10 + j)
                          ).result(timeout=60)
        for k, v, o in zip(kf.tolist(), vf.tolist(),
                           np.asarray(okf).tolist()):
            if o:
                fenced_pairs.append((k, v))
    assert fenced_pairs, "the stale primary acked nothing post-fence"
    out["stale_acks_post_fence"] = len(fenced_pairs)

    # heal: the next fence check sees the live table — typed
    chaos.heal()
    try:
        srv3.submit("insert", f_slice[:2],
                    f_slice[:2] ^ np.uint64(1), tenant="stale",
                    rid=(0xFE << 32) | 99).result(timeout=60)
        raise AssertionError("stale-primary write after the heal was "
                             "NOT fenced")
    except ShermanError as e:
        assert _unwrap(e, StalePrimaryError) is not None, \
            f"fence raised untyped {type(e).__name__}: {e}"
    out["stale_rejected_typed"] = True
    srv3.kill()
    fenced_n = group.count_fenced_suffix()
    assert fenced_n > 0, "no fenced suffix behind the fence point"
    out["fenced_suffix_records"] = fenced_n

    # -- resume: promoted front door + the fenced-merge probe ----------------
    win = group.promoted
    plane2 = RecoveryPlane(win.cluster, win.tree, win.eng,
                           os.path.join(root, "promoted"),
                           group_commit_ms=2.0)
    plane2.checkpoint_base()
    srv2 = front_door(win.eng)
    adopted = srv2.seed_dedup(group.promoted_window())
    _g0, f0 = srv2.submit("read", keys[:64]).result(timeout=60)
    assert np.asarray(f0).all()
    out["availability_gap_ms"] = group.note_resumed()
    out["dedup"] = {"adopted": adopted}
    assert adopted > 0, "promotion adopted an empty dedup window"

    def read_all(ks: np.ndarray):
        wmax = max(widths)
        parts = [srv2.submit("read", ks[i:i + wmax]).result(
            timeout=120) for i in range(0, ks.size, wmax)]
        return (np.concatenate([np.asarray(g) for g, _ in parts]),
                np.concatenate([np.asarray(f) for _, f in parts]))

    # fenced acks provably rejected: BEFORE the re-drive, no fenced
    # (key, value) pair is visible in the promoted state
    probe = A.check_fenced_rejected(read_all, fenced_pairs)
    out["fenced_acks_merged"] = probe["merged"]
    assert probe["merged"] == 0, \
        f"fenced acks merged: {probe['violations'][:3]}"

    # the contract's second half: the retrying client re-drives the
    # fenced writes through the NEW primary's dedup window (fresh
    # rids — the fenced rids belong to the dead window)
    cl2 = RetryingClient(srv2, tenant="stale",
                         policy=RetryPolicy(max_attempts=6),
                         seed=77)
    kf = np.asarray([k for k, _ in fenced_pairs], np.uint64)
    vf = np.asarray([v for _, v in fenced_pairs], np.uint64)
    t_inv = time.perf_counter()
    okr = cl2.insert(kf, vf, rid=cl2.next_rid())
    t_resp = time.perf_counter()
    with ev_lock:
        for k, v, o in zip(kf.tolist(), vf.tolist(),
                           np.asarray(okr).tolist()):
            if o:
                acked[k] = v
                events.append((k, A.OP_INSERT, t_inv, t_resp, v,
                               True))
    out["redriven"] = int(np.asarray(okr).sum())
    assert out["redriven"] == len(fenced_pairs), \
        "re-drive through the new primary dropped writes"

    # -- lost acks: every owed ack served by the promoted primary ------------
    ak = np.asarray(sorted(acked), np.uint64)
    av = np.asarray([acked[int(k)] for k in ak], np.uint64)
    t_inv = time.perf_counter()
    got, found = read_all(ak)
    t_resp = time.perf_counter()
    lost = int((~found).sum()) + int((got[found] != av[found]).sum())
    with ev_lock:
        for k, g, f in zip(ak.tolist(), got.tolist(),
                           found.tolist()):
            events.append((int(k), A.OP_READ, t_inv, t_resp,
                           int(g) if f else None, bool(f)))
    pr = untouched[:: max(1, untouched.size // 256)]
    gotp, foundp = read_all(pr)
    lost += int((~foundp).sum()) + int(
        (gotp[foundp] != (pr ^ np.uint64(SALT))[foundp]).sum())
    out["lost_acks"] = lost
    assert lost == 0, f"{lost} acked ops lost across the partition"

    # -- duplicate acks: pre-fence rids retried re-ack, never re-apply -------
    duplicate_acks = 0
    retried = 0
    for rid, (tenant, kreq, vreq, okl) in \
            list(rid_ledger.items())[-6:]:
        if not okl.any():
            continue
        retried += 1
        fut = srv2.submit("insert", kreq, vreq, tenant=tenant,
                          rid=rid)
        okr = fut.result(timeout=60)
        if not fut.deduped or not np.array_equal(okr, okl):
            duplicate_acks += 1
            continue
        got, found = srv2.submit("read", kreq).result(timeout=60)
        stomped = sum(
            1 for k, g, f in zip(kreq.tolist(),
                                 np.asarray(got).tolist(),
                                 np.asarray(found).tolist())
            if int(k) in acked and f and int(g) != acked[int(k)])
        if stomped:
            duplicate_acks += 1
    out["retried"] = retried
    out["duplicate_acks"] = duplicate_acks
    assert retried > 0, "drill retried nothing across the partition"
    assert duplicate_acks == 0, \
        f"{duplicate_acks} retried writes re-applied"
    srv2.drain()
    plane2.close()

    # -- offline linearizability over the surviving history ------------------
    initial = {int(k): (True, int(v)) for k, v in zip(keys, vals)}
    verdict = A.check_events(events, initial=initial)
    out["audit"] = {
        "events": verdict["events"], "keys": verdict["keys"],
        "reads_checked": verdict["reads"],
        "violations": len(verdict["violations"]),
    }
    out["linearizable"] = bool(verdict["linearizable"])
    assert verdict["linearizable"], \
        f"history not linearizable: {verdict['violations'][:3]}"
    assert verdict["reads"] > 0, "audit checked no reads"
    jsonl = os.path.join(root, "history.jsonl")
    A.dump_jsonl(events, jsonl)
    out["history_jsonl"] = jsonl

    # -- the partition receipt ------------------------------------------------
    st = group.stats()
    out["repl"] = {
        "followers": st["followers"],
        "applied_records": st["applied_records"],
        "epoch": st["epoch"],
        "tail_stalls": st["tail_stalls"],
        "chaos_detected": st["chaos_detected"],
        "quarantined": st["quarantined"],
        "divergences": st["divergences"],
        "quorum": {
            "ack_quorum": 2,
            "acks": st["quorum_acks"],
            "timeouts": st["quorum_timeouts"],
            "wait_ms": st["quorum_wait_ms"],
            "p50_ms_primary_only":
                out["quorum_latency"]["p50_ms_primary_only"],
            "p50_ms_quorum":
                out["quorum_latency"]["p50_ms_quorum"],
            "delta_ms": out["quorum_latency"]["delta_ms"],
        },
    }
    out["diverged_followers_unrepaired"] = ae.unrepaired()
    out["chaos"] = {"injected": chaos.injected,
                    "detected": chaos.detected,
                    "faults": chaos.describe()}
    assert chaos.injected >= 3, "the fault layer fired almost nothing"
    out["elapsed_s"] = round(time.time() - t_start, 1)
    out["ok"] = True
    line = json.dumps(out)
    print(line)
    receipt = os.environ.get("SHERMAN_PARTITION_RECEIPT")
    if receipt:
        with open(receipt, "w") as f:
            f.write(line + "\n")
    print("PARTITION-DRILL PASS", file=sys.stderr)
    return out


if __name__ == "__main__":
    main()
