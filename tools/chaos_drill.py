#!/usr/bin/env python
"""Data-plane chaos drill: inject -> detect -> recover -> re-validate.

The end-to-end rehearsal of the data-plane failure story (the
control-plane twin is ``tests/test_failure.py``):

  phase A  a client dies holding a page lock (chaos ``wedge_lock`` with
           a dead lease).  The HOST path detects the dead holder inside
           its spin loop and revokes the lease (``lease.revoked`` > 0);
           re-wedged, the ENGINE's bounded lock retry detects it after
           ``lock_retry_rounds`` blocked rounds and revokes through
           ``_recover_wedged_locks`` — the insert completes either way.
  phase B  a lock is wedged by a LIVE lease: the engine must NOT revoke
           it; the write is rejected with the typed ST_LOCK_TIMEOUT
           outcome after the bounded budget (no silent budget burn, no
           hang).
  phase C  pool corruption (torn front/rear page versions + a flipped
           entry-version half — the classes Sherman's CONFIG_ENABLE_CRC
           guards).  The online scrubber detects both
           (``scrub.violations`` > 0), quarantines the page, and flips
           the engine to read-only degraded mode: writes raise the
           typed DegradedError while searches keep serving.
  recover  the documented degraded-mode exit: restore the pre-fault
           checkpoint into a fresh cluster, re-validate
           (``check_structure_device`` green), verify every key.

Runs on the CPU mesh anywhere (``bench.py --chaos-drill`` forwards
here; ``scripts/chaos_ci.sh`` pins it in CI).  Prints ONE JSON line:
``{"metric": "chaos_drill", "ok": true, ...}``.

Env knobs: SHERMAN_DRILL_KEYS (default 4000), SHERMAN_DRILL_NODES
(default 4), SHERMAN_CHAOS_SEED (default 7 — seeds the random fault
sprinkle phase C adds on top of the targeted faults).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

from common import build_cluster, pages_for_keys, setup_platform


def main(argv=None) -> dict:
    p = argparse.ArgumentParser()
    p.add_argument("--keys", type=int,
                   default=int(os.environ.get("SHERMAN_DRILL_KEYS", 4000)))
    p.add_argument("--nodes", type=int,
                   default=int(os.environ.get("SHERMAN_DRILL_NODES", 4)))
    p.add_argument("--seed", type=int,
                   default=int(os.environ.get("SHERMAN_CHAOS_SEED", 7)))
    a = p.parse_args(argv)
    setup_platform(a.nodes)

    from sherman_tpu import chaos as CH
    from sherman_tpu import obs
    from sherman_tpu.config import TreeConfig
    from sherman_tpu.models import batched
    from sherman_tpu.models.btree import Tree
    from sherman_tpu.models.scrub import Scrubber
    from sherman_tpu.models.validate import check_structure_device
    from sherman_tpu.utils import checkpoint as CK

    t0 = time.time()
    out: dict = {"metric": "chaos_drill", "seed": a.seed, "ok": False}
    # black box: the drill's flight recorder holds ONLY its own story
    # (fresh ring), and the receipt below proves the dump shows inject
    # -> degrade -> recover in order — the postmortem contract
    rec = obs.get_recorder()
    rec.clear()
    cluster, tree, eng = build_cluster(
        a.nodes, pages_for_keys(a.keys), batch_per_node=512,
        locks_per_node=1024, chunk_pages=64)
    eng.tcfg = TreeConfig(sibling_chase_budget=1, lock_retry_rounds=2)
    dsm = cluster.dsm
    keys = np.unique(np.random.default_rng(3).integers(
        1, 1 << 56, int(a.keys * 1.05), dtype=np.uint64))[:a.keys]
    vals = keys ^ np.uint64(0xDEADBEEF)
    batched.bulk_load(tree, keys, vals)
    eng.attach_router()
    check_structure_device(tree)
    ckpt = os.path.join(tempfile.mkdtemp(prefix="sherman_drill_"),
                        "pre_fault.npz")
    CK.checkpoint(cluster, ckpt)
    victim = int(tree._descend(int(keys[a.keys // 2]))[0])
    la = tree._lock_word_addr(victim)
    snap0 = obs.snapshot()

    def wedge(owner=CH.DEAD_OWNER_TAG, epoch=CH.DEAD_OWNER_EPOCH):
        plan = CH.FaultPlan([CH.Fault(kind="wedge_lock", step=0, addr=la,
                                      owner=owner, epoch=epoch)])
        dsm.install_chaos(plan)
        dsm.read_word(0, 0)  # one host step fires the wedge
        dsm.install_chaos(None)

    # -- phase A: dead-lease wedge, host-path revocation ---------------------
    wedge()
    la_held = tree._lock(victim)
    tree._unlock(la_held)
    d = obs.delta(snap0, obs.snapshot())
    out["host_revoked"] = int(d.get("lease.revoked", 0))
    assert out["host_revoked"] >= 1, "host spin path never revoked"

    # -- phase A2: dead-lease wedge, engine bounded-retry revocation ---------
    wedge()
    snap1 = obs.snapshot()
    band = keys[a.keys // 2: a.keys // 2 + 8]
    st = eng.insert(band, band)
    d = obs.delta(snap1, obs.snapshot())
    out["engine_revoked"] = int(d.get("lease.revoked", 0))
    out["engine_insert"] = {k: v for k, v in st.items()
                            if k != "lock_timeout_keys"}
    assert out["engine_revoked"] >= 1, "engine never revoked the wedge"
    assert st["lock_timeouts"] == 0 and st["applied"] + st[
        "superseded"] + st["host_path"] == band.size

    # -- phase B: LIVE-lease wedge -> typed lock-timeout rejection -----------
    live_ctx = cluster.register_client()
    import sherman_tpu.parallel.dsm as D
    dsm.write_word(la, 0, live_ctx.lease, space=D.SPACE_LOCK)
    st = eng.insert(band[:4], band[:4])
    out["lock_timeouts"] = st["lock_timeouts"]
    assert st["lock_timeouts"] == 4, f"expected typed rejection: {st}"
    dsm.write_word(la, 0, 0, space=D.SPACE_LOCK)  # holder releases

    # -- phase C: corruption -> scrub detect -> quarantine + degrade ---------
    scr = Scrubber(eng, interval=1)
    clean = scr.scrub()
    assert clean["violations"] == 0, f"pre-fault scrub dirty: {clean}"
    plan = CH.FaultPlan([
        CH.Fault(kind="torn_page", step=0, addr=victim),
        CH.Fault(kind="flip_entry_ver", step=0, addr=victim, slot=2),
        # plus a seeded random sprinkle on other live pages
        *CH.FaultPlan.random(a.seed, n_faults=2, step_hi=1).faults,
    ], seed=a.seed)
    dsm.install_chaos(plan)
    dsm.read_word(0, 0)
    dsm.install_chaos(None)
    res = scr.scrub()
    out["scrub"] = {"pages_checked": res["pages_checked"],
                    "violations": res["violations"],
                    "classes": res["classes"],
                    "quarantined": res["quarantined"]}
    assert res["violations"] >= 1, "scrubber missed injected corruption"
    assert eng.degraded, "engine did not degrade on structural damage"
    try:
        eng.insert(band, band)
        raise AssertionError("degraded engine accepted a write")
    except batched.DegradedError as e:
        out["degraded_reason"] = e.reason
    v, f = eng.search(keys[:256])
    assert f.all(), "degraded engine dropped reads"
    out["degraded_reads_served"] = int(f.sum())

    # -- recover: checkpoint restore (the documented exit) -------------------
    cluster2 = CK.restore(ckpt)
    tree2 = Tree(cluster2)
    eng2 = batched.BatchedEngine(tree2, batch_per_node=512,
                                 tcfg=TreeConfig(sibling_chase_budget=1))
    eng2.attach_router()
    info = check_structure_device(tree2)
    assert info["keys"] == a.keys
    v, f = eng2.search(keys)
    assert f.all()
    np.testing.assert_array_equal(v, vals)
    st = eng2.insert(band, band)  # writes accepted again
    assert st["applied"] + st["superseded"] == band.size
    out["restored"] = info
    d = obs.delta(snap0, obs.snapshot())
    out["chaos_injected"] = int(d.get("chaos.faults_injected", 0))

    # -- black box: dump + assert the postmortem ORDER -----------------------
    # the bundle must show the injected fault, the degraded transition
    # and the recovery step in sequence — scattered counters cannot
    bb_dir = os.environ.get("SHERMAN_BLACKBOX_DIR") or os.path.join(
        tempfile.mkdtemp(prefix="sherman_drill_"), "blackbox")
    bb_path = rec.dump("chaos_drill", bb_dir)
    evs = rec.events()

    def first_seq(kind, after=-1):
        return next((e["seq"] for e in evs if e["kind"] == kind
                     and e["seq"] > after), None)

    s_inject = first_seq("chaos.inject")
    s_degraded = first_seq("engine.degraded_enter")
    s_typed = first_seq("engine.typed_error")
    s_restore = first_seq("checkpoint.restore")
    assert s_inject is not None, "no chaos.inject event in the black box"
    assert s_degraded is not None and s_degraded > s_inject, \
        "degraded transition missing or out of order in the black box"
    assert s_restore is not None and s_restore > s_degraded, \
        "recovery step missing or out of order in the black box"
    with open(bb_path) as f:
        bundle = json.load(f)
    bkinds = [e["kind"] for e in bundle["otherData"]["flight_events"]]
    assert "chaos.inject" in bkinds and "engine.degraded_enter" in bkinds
    out["blackbox"] = {
        "path": bb_path,
        "events": len(evs),
        "order": {"inject": s_inject, "degraded": s_degraded,
                  "typed_error": s_typed, "restore": s_restore},
        "ordered": True,
    }

    out["elapsed_s"] = round(time.time() - t0, 1)
    out["ok"] = True
    print(json.dumps(out))
    print("CHAOS-DRILL PASS", file=sys.stderr)
    return out


if __name__ == "__main__":
    main()
