#!/usr/bin/env python
"""Insert-step phase profiler — where does the write path's time go?

The insert step (``batched.insert_step_spmd``) is: routed descent ->
page-snapshot gather -> multi-operand dedup sort -> rank/verdict scans ->
one-hot fver extract -> fused write-back scatter.  This driver measures
the FULL step and each phase in isolation at a configurable row count,
so the published per-phase breakdown (BENCHMARKS.md) is reproducible.

Methodology: every per-call sync through the remote-access tunnel costs
~100+ ms, which swamps per-call timings of ms-scale phases.  Each phase
is therefore run K and 2K times CHAINED inside one jitted fori_loop
(data-dependent carries so XLA cannot elide the repeats), and the cost
is the difference quotient (t_2K - t_K) / K — the sync overhead cancels
exactly.

Usage:  python tools/profile_insert.py [--rows N] [--keys N] [--k K]
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from common import build_cluster, pages_for_keys


def main(argv=None) -> dict:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--rows", type=int, default=2_097_152)
    p.add_argument("--keys", type=int, default=2_000_000)
    p.add_argument("--k", type=int, default=8)
    a = p.parse_args(argv)

    import jax
    import jax.numpy as jnp
    from jax import lax

    from sherman_tpu import config as C
    from sherman_tpu.models import batched
    from sherman_tpu.ops import bits

    M, K = a.rows, a.k
    cluster, tree, eng = build_cluster(1, pages_for_keys(a.keys), M)
    rng = np.random.default_rng(7)
    keys = np.unique(rng.integers(1, 1 << 63, int(a.keys * 1.05),
                                  dtype=np.uint64))[:a.keys]
    batched.bulk_load(tree, keys, keys)
    router = eng.attach_router()
    dsm = tree.dsm
    P = dsm.pool.shape[0]
    print(f"# rows={M} keys={a.keys} pages={P} K={K}", file=sys.stderr)

    bk = keys[rng.integers(0, a.keys, M)]
    khi, klo = bits.keys_to_pairs(bk)
    shard = dsm.shard
    d = lambda x: jax.device_put(x, shard)
    khi_d, klo_d = d(khi), d(klo)
    vhi_d, vlo_d = d(khi ^ np.int32(0xBEE)), d(klo)
    act_d = d(np.ones(M, bool))
    start = router.host_start(khi, klo)
    start_d = d(start)
    root = np.int32(tree._root_addr)
    rows_np = np.asarray(bits.addr_page(start)).astype(np.int32)
    rows_d = d(rows_np)
    res = {}

    def drain(x):
        np.asarray(jnp.ravel(jax.tree_util.tree_leaves(x)[0])[0])

    def chain_cost(name, mk_loop, *args):
        """(t_2K - t_K)/K of a jitted fori_loop phase chain."""
        import functools
        spans = {}
        for reps in (K, 2 * K):
            fn = jax.jit(functools.partial(mk_loop, reps=reps),
                         static_argnames=())
            out = fn(*args)
            drain(out)
            best = []
            for _ in range(3):
                t0 = time.perf_counter()
                out = fn(*args)
                drain(out)
                best.append(time.perf_counter() - t0)
            spans[reps] = min(best)
        ms = (spans[2 * K] - spans[K]) / K * 1e3
        res[name] = ms
        print(f"{name:32s} {ms:9.2f} ms", flush=True)

    # --- full insert step + search floor, chained inside ONE jit -----------
    # (queueing many separate step programs through the access tunnel is
    # flaky past a handful in flight; an in-jit fori_loop sidesteps both
    # that and the per-call sync)
    iters = eng._iters()

    def mk_insert_loop(update_only):
        def insert_loop(pool, counters, reps):
            def body(i, st):
                pool, counters, acc = st
                pool, counters, status = batched.insert_step_spmd(
                    pool, dsm.locks, counters, khi_d, klo_d,
                    vhi_d ^ i, vlo_d, root, act_d, start_d, None,
                    cfg=eng.cfg, iters=iters, update_only=update_only)
                return pool, counters, acc + jnp.sum(status)
            _, _, acc = lax.fori_loop(0, reps, body,
                                      (pool, counters, jnp.int32(0)))
            return acc
        return insert_loop

    # one real engine step first: correctness spot check
    ifn = eng._get_insert(iters, True, with_fresh=False, update_only=True)
    dsm.pool, dsm.counters, dsm.dirty, st = ifn(
        dsm.pool, dsm.locks, dsm.counters, dsm.dirty, khi_d, klo_d,
        vhi_d, vlo_d, root, act_d, start_d)
    ok = np.isin(np.asarray(st), (batched.ST_APPLIED, batched.ST_SUPERSEDED))
    assert ok.all(), f"profile batch: {np.unique(np.asarray(st))}"
    chain_cost("insert_step_update_only", mk_insert_loop(True),
               dsm.pool, dsm.counters)
    chain_cost("insert_step_general", mk_insert_loop(False),
               dsm.pool, dsm.counters)

    def search_loop(pool, counters, reps):
        # roll the (key, seed) pairs per iteration so the read-only body
        # is not loop-invariant (XLA would hoist it and time nothing);
        # rolling keeps every key/seed pair intact — identical work
        def body(i, st):
            counters, acc = st
            counters, done, f, vh, vl = batched.search_routed_spmd(
                pool, counters, jnp.roll(khi_d, i), jnp.roll(klo_d, i),
                root, act_d, jnp.roll(start_d, i),
                cfg=eng.cfg, iters=iters)
            return counters, acc + jnp.sum(f)
        _, acc = lax.fori_loop(0, reps, body, (counters, jnp.int32(0)))
        return acc

    chain_cost("search_step_same_width", search_loop, dsm.pool,
               dsm.counters)

    # --- isolated phases (chained in-jit) ----------------------------------
    def gather_loop(pool, rows, reps):
        def body(i, st):
            acc, r = st
            pg = pool[(r + i) % P]
            return acc + pg[:, 0], r
        acc, _ = lax.fori_loop(0, reps, body,
                               (jnp.zeros(M, jnp.int32), rows))
        return acc

    chain_cost("page_snapshot_gather", gather_loop, dsm.pool, rows_d)

    def sort6_loop(pk, kh, kl, reps):
        idx0 = jnp.arange(M, dtype=jnp.int32)
        f0 = jnp.zeros(M, bool)
        fc0 = jnp.full(M, 5, jnp.int32)

        def body(i, st):
            pk, kh, kl = st
            sp, skh, skl, _, _, _ = lax.sort(
                (pk ^ i, kh, kl, idx0, f0, fc0), num_keys=3)
            return sp, skh, skl
        return lax.fori_loop(0, reps, body, (pk, kh, kl))

    chain_cost("dedup_sort_6op", sort6_loop, rows_d, khi_d, klo_d)

    def scans_loop(win, reps):
        idx0 = jnp.arange(M, dtype=jnp.int32)

        def body(i, st):
            w, acc = st
            head = jnp.concatenate([jnp.ones(1, bool), w[1:] != w[:-1]])
            cum = jnp.cumsum(head.astype(jnp.int32))
            base = lax.associative_scan(
                jnp.maximum, jnp.where(head, cum - 1, -1))
            enc = lax.associative_scan(
                jnp.maximum, jnp.where(head, idx0 * 2 + 1, -1))
            return w + 1, acc + base + enc
        _, acc = lax.fori_loop(0, reps, body,
                               (win, jnp.zeros(M, jnp.int32)))
        return acc

    chain_cost("verdict_scans_x3", scans_loop, rows_d)

    def onehot_loop(pool, rows, slot, reps):
        def body(i, acc):
            pg = pool[(rows + i) % P]
            blk = pg[:, C.L_VER_W:C.L_VER_W + C.LEAF_CAP]
            oh = jnp.arange(C.LEAF_CAP)[None, :] == slot[:, None]
            return acc + jnp.sum(jnp.where(oh, blk, 0), axis=-1)
        return lax.fori_loop(0, reps, body, jnp.zeros(M, jnp.int32))

    slot_d = d(rng.integers(0, C.LEAF_CAP, M).astype(np.int32))
    chain_cost("gather_plus_onehot_ver", onehot_loop, dsm.pool, rows_d,
               slot_d)

    field_w = np.array([C.L_VER_W, C.L_KHI_W, C.L_KLO_W, C.L_VHI_W,
                        C.L_VLO_W, C.W_FRONT_VER, C.W_REAR_VER,
                        C.W_NKEYS], np.int32)

    def scatter_loop_w(width):
        idx = d((rows_np[:, None] * C.PAGE_WORDS
                 + field_w[None, :width]).astype(np.int32))
        ent = d(rng.integers(1, 1 << 30, (M, width)).astype(np.int32))

        def loop(pool, idx, ent, reps):
            def body(i, pl):
                flat = pl.reshape(-1)
                flat = flat.at[idx.reshape(-1)].set(
                    (ent ^ i).reshape(-1), mode="drop")
                return flat.reshape(P, C.PAGE_WORDS)
            return lax.fori_loop(0, reps, body, pool)
        return loop, idx, ent

    for width in (8, 6, 4):
        loop, idx, ent = scatter_loop_w(width)
        chain_cost(f"writeback_scatter_{width}w", loop, dsm.pool, idx, ent)

    for k, v in sorted(res.items(), key=lambda kv: -kv[1]):
        print(f"# {k:32s} {v:9.2f} ms", file=sys.stderr)
    return res


if __name__ == "__main__":
    main()
