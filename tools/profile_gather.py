#!/usr/bin/env python
"""Page-engine kernel profiler — the pallas-vs-xla gather/scatter A/B.

Times the three ops/pallas_page.py kernels against their XLA twins at a
configurable row count, side by side, with the chained-delta method from
``tools/profile_insert.py`` (each phase runs K and 2K times chained
inside one jitted fori_loop with data-dependent carries; cost =
(t_2K - t_K)/K, which cancels the per-call sync — ~100 ms through the
access tunnel — exactly):

- ``descent_round``   one fused gather+pick round (the routed-search
                      descent floor: 54.7-55.4 ms at 2 M rows on the
                      XLA path, BENCHMARKS.md phase table)
- ``snapshot_gather`` the apply path's page snapshot (~28 ms XLA)
- ``writeback_3w/5w`` the update/insert write-back (XLA: ~13.5 ms per
                      word lane)

Emits a table on stderr, ONE JSON line on stdout
({phase: {xla_ms, pallas_ms, ratio}}), and records each timing as a
``kernels.{phase}_{impl}_ms`` obs histogram so bench artifacts can carry
the same receipts (`bench.py` embeds them via ``kernel_phase_ms``).

On non-TPU backends the pallas kernels run in INTERPRETER mode — orders
of magnitude slower, useful only as a mechanics smoke (CI runs it at
tiny --rows); the chip capture is the number that decides the
``gather_impl`` knob.  See BENCHMARKS.md "Chip-session queue".

Usage:  python tools/profile_gather.py [--rows N] [--keys N] [--k K]
                                       [--impls xla,pallas]
"""

from __future__ import annotations

import argparse
import functools
import json
import sys
import time

import numpy as np

from common import build_cluster, pages_for_keys


def phase_table(pool, addr, khi, klo, *, k: int = 4,
                impls=("xla", "pallas"), rows: int | None = None) -> dict:
    """Chained-delta ms per phase per impl on live arrays.

    ``pool`` [P, PAGE_WORDS]; ``addr`` packed page addresses [M] (the
    descent seeds AND the gather/scatter row source); khi/klo [M] key
    words.  Returns {phase: {impl: ms}} and records the matching
    ``kernels.*_ms`` obs histograms.  The write-back phases scatter
    random entries into the carried pool COPY inside the jit — the
    caller's pool handle is never mutated, but do not reuse the timed
    copies.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from sherman_tpu import config as C
    from sherman_tpu import obs
    from sherman_tpu.ops import bits
    from sherman_tpu.ops import pallas_page as PP

    M = addr.shape[0] if rows is None else rows
    addr = jnp.asarray(addr[:M])
    khi, klo = jnp.asarray(khi[:M]), jnp.asarray(klo[:M])
    P = pool.shape[0]
    pages = bits.addr_page(addr)
    act = jnp.ones(M, bool)
    rng = np.random.default_rng(3)
    slots = jnp.asarray(rng.integers(0, C.LEAF_CAP, M).astype(np.int32))
    res: dict = {}

    def drain(x):
        np.asarray(jnp.ravel(jax.tree_util.tree_leaves(x)[0])[0])

    def chain_cost(phase, impl, mk_loop, *args):
        spans = {}
        for reps in (k, 2 * k):
            fn = jax.jit(functools.partial(mk_loop, reps=reps))
            out = fn(*args)
            drain(out)
            best = []
            for _ in range(3):
                t0 = time.perf_counter()
                out = fn(*args)
                drain(out)
                best.append(time.perf_counter() - t0)
            spans[reps] = min(best)
        ms = (spans[2 * k] - spans[k]) / k * 1e3
        res.setdefault(phase, {})[impl] = ms
        obs.histogram(f"kernels.{phase}_{impl}_ms").record(ms)
        print(f"{phase:20s} {impl:7s} {ms:9.2f} ms", file=sys.stderr,
              flush=True)

    # --- fused descent round (gather + in-page pick) -----------------------
    def mk_descent(impl):
        fn = (PP.descent_round if impl == "pallas"
              else PP.descent_round_xla)

        def loop(pool, addr, reps):
            def body(i, st):
                a, acc = st
                nxt, is_leaf, chase, ok, f, vh, vl = fn(
                    pool, a, khi, klo, act)
                # data-dependent carry: the next round starts where this
                # one routed (wrapped into the pool so rows stay valid)
                a2 = jnp.where(ok & ~is_leaf, nxt, a)
                a2 = bits.addr_page(a2 + i) % P
                return a2, acc + jnp.sum(vh ^ vl)
            _, acc = lax.fori_loop(0, reps, body, (addr, jnp.int32(0)))
            return acc
        return loop

    # --- snapshot gather ----------------------------------------------------
    def mk_gather(impl):
        fn = PP.gather_pages if impl == "pallas" else PP.gather_pages_xla

        def loop(pool, rows, reps):
            def body(i, st):
                acc, r = st
                pg = fn(pool, (r + i) % P)
                return acc + pg[:, 0], r
            acc, _ = lax.fori_loop(0, reps, body,
                                   (jnp.zeros(M, jnp.int32), rows))
            return acc
        return loop

    # --- multi-lane write-back ---------------------------------------------
    def mk_writeback(impl, lanes):
        ent0 = jnp.asarray(
            rng.integers(1, 1 << 30, (M, len(lanes))).astype(np.int32))
        fn = PP.writeback if impl == "pallas" else PP.writeback_xla

        def loop(pool, rows, reps):
            def body(i, pl_):
                return fn(pl_, rows, slots, act, ent0 ^ i,
                          field_w=lanes)
            return lax.fori_loop(0, reps, body, pool)
        return loop

    upd = (C.L_VER_W, C.L_VHI_W, C.L_VLO_W)
    ins = (C.L_VER_W, C.L_KHI_W, C.L_KLO_W, C.L_VHI_W, C.L_VLO_W)
    safe_rows = jnp.clip(pages, 0, P - 1)
    for impl in impls:
        chain_cost("descent_round", impl, mk_descent(impl), pool, addr)
        chain_cost("snapshot_gather", impl, mk_gather(impl), pool,
                   safe_rows)
        chain_cost("writeback_3w", impl, mk_writeback(impl, upd), pool,
                   safe_rows)
        chain_cost("writeback_5w", impl, mk_writeback(impl, ins), pool,
                   safe_rows)
    for phase, by_impl in res.items():
        if "xla" in by_impl and "pallas" in by_impl and by_impl["xla"]:
            by_impl["ratio"] = by_impl["pallas"] / by_impl["xla"]
    return res


def main(argv=None) -> dict:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--rows", type=int, default=2_097_152)
    p.add_argument("--keys", type=int, default=2_000_000)
    p.add_argument("--k", type=int, default=4)
    p.add_argument("--impls", default="xla,pallas")
    a = p.parse_args(argv)

    import jax

    from sherman_tpu.models import batched
    from sherman_tpu.ops import bits

    impls = tuple(s for s in a.impls.split(",") if s)
    cluster, tree, eng = build_cluster(1, pages_for_keys(a.keys), a.rows)
    rng = np.random.default_rng(7)
    keys = np.unique(rng.integers(1, 1 << 63, int(a.keys * 1.05),
                                  dtype=np.uint64))[:a.keys]
    batched.bulk_load(tree, keys, keys)
    router = eng.attach_router()
    dsm = tree.dsm
    backend = jax.default_backend()
    print(f"# rows={a.rows} keys={a.keys} pages={dsm.pool.shape[0]} "
          f"K={a.k} backend={backend}"
          + (" (pallas INTERPRETED — mechanics only)"
             if backend != "tpu" else ""), file=sys.stderr)

    bk = keys[rng.integers(0, a.keys, a.rows)]
    khi, klo = bits.keys_to_pairs(bk)
    start = router.host_start(khi, klo)
    d = lambda x: jax.device_put(x, dsm.shard)
    res = phase_table(dsm.pool, d(start), d(khi), d(klo), k=a.k,
                      impls=impls)
    out = {
        "metric": "pallas_vs_xla_page_kernels",
        "rows": a.rows,
        "keys": a.keys,
        "backend": backend,
        "pallas_interpreted": backend != "tpu",
        "phases": {ph: {k2: round(v, 3) for k2, v in by.items()}
                   for ph, by in res.items()},
    }
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
