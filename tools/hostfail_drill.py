#!/usr/bin/env python
"""Host-failure drill: lease expiry under traffic, zombie-host
fencing, and chain adoption by the surviving host.

The seventh end-to-end rehearsal (chaos = detection, recovery =
durability, reshard = capacity, contract = the front door, failover =
replication, multihost = the service plane) — this one pins the
HOST-LOSS TOLERANCE plane (``sherman_tpu/hostlease.py``):

  phase 1  TWO emulated host contexts in one process behind one
           ``MultihostService`` (per-host chains ``-h0-``/``-h1-`` in
           one shared directory), plus the cross-host LEASE TABLE:
           each host registers a durable heartbeat record and a
           renewer thread re-stamps it; every engine's journal gate is
           wrapped by a ``HostFence`` bound to the host's lease epoch.
  traffic  open-loop writers + a deleter (exactly-once rids) +
           readers hammer the routed front door; one probe rid's
           acked result is remembered for the post-adoption re-ack
           pin.
  freeze   host 0 freezes mid-traffic (``HostChaos``): the dispatch
           seam refuses its sub-batches typed (``HostDownError``),
           its renewals are suppressed, and ONE in-flight append
           pins its lease view — the frozen host cannot watch its
           own epoch get bumped.  Its lease expires UNDER TRAFFIC.
  adopt    host 1 adopts: fence point captured (last clean frame
           boundary — the torn half-frame appended at the freeze is
           about to be truncated), ``begin`` journaled, epoch bumped
           durably, host 0's chain recovered (torn tail truncated,
           stale sweep deferred), dedup window re-seeded into a fresh
           front door, ownership overlay published, ``done``
           journaled.  The availability gap (freeze -> first
           successful routed op on the dead keyspace) is published.
  zombie   host 0 revives as a ZOMBIE: its pinned lease view still
           says epoch 1, so its stale acks keep landing durably —
           PAST the fence point, where ``count_fenced_suffix`` counts
           them and the read-back audit proves none ever merged.  On
           heal the bump becomes visible and the next append raises
           the typed ``StaleHostError``.
  audit    retried probe rid re-acks its ORIGINAL result through the
           adopter's re-seeded window; the merged acked-op ledger
           reads back through the adopted door (``lost_acks == 0``);
           the whole routed history checks linearizable offline.

Runs on the CPU mesh anywhere (``bench.py --hostfail-drill`` forwards
here; ``scripts/hostfail_ci.sh`` pins it in CI).  Prints ONE JSON line
``{"metric": "hostfail_drill", "ok": true, "lost_acks": 0,
"duplicate_acks": 0, "linearizable": true, "fenced_acks_merged": 0,
"unadopted_dead_hosts": 0, "availability_gap_ms": ..., ...}`` and
mirrors it to ``SHERMAN_HOSTFAIL_RECEIPT`` when set.  perfgate treats
the committed receipt as a robustness artifact: never
throughput-gated, but ``lost_acks``/``duplicate_acks``/
``fenced_acks_merged``/``unadopted_dead_hosts`` nonzero or
``linearizable == false`` is a marginless hard red.  Env knobs:
SHERMAN_DRILL_KEYS (default 4000), SHERMAN_CHAOS_SEED,
SHERMAN_DRILL_SECS, SHERMAN_HOST_LEASE_S (drill default 0.5).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

from common import build_cluster, pages_for_keys, setup_platform

SALT = 0x30057FEB  # bulk-load value stamp (key ^ SALT)
PROBE_RID = 0x51C0FFEE  # the exactly-once re-ack probe


def _chunked_svc_read(svc, keys: np.ndarray, width: int = 512):
    """Routed point reads in dispatch-sized chunks -> (values, found)."""
    vs, fs = [], []
    for i in range(0, keys.size, width):
        v, f = svc.submit("read", keys[i:i + width]).result(timeout=120)
        vs.append(np.asarray(v, np.uint64))
        fs.append(np.asarray(f, bool))
    return np.concatenate(vs), np.concatenate(fs)


def main(argv=None) -> dict:
    p = argparse.ArgumentParser()
    p.add_argument("--keys", type=int,
                   default=int(os.environ.get("SHERMAN_DRILL_KEYS", 4000)))
    p.add_argument("--seed", type=int,
                   default=int(os.environ.get("SHERMAN_CHAOS_SEED", 7)))
    p.add_argument("--secs", type=float,
                   default=float(os.environ.get("SHERMAN_DRILL_SECS", 2.0)))
    p.add_argument("--lease-s", type=float,
                   default=float(os.environ.get("SHERMAN_HOST_LEASE_S",
                                                0.5)))
    p.add_argument("--dir", default=None,
                   help="drill directory (default: a tempdir)")
    a = p.parse_args(argv)
    # one device per emulated host (the failover drill's lesson)
    setup_platform(1)

    from sherman_tpu import audit as A
    from sherman_tpu import obs
    from sherman_tpu.chaos import HostChaos
    from sherman_tpu.errors import ShermanError
    from sherman_tpu.hostlease import (HostFailover, HostFence,
                                       HostLeaseTable, StaleHostError,
                                       count_fenced_suffix)
    from sherman_tpu.models import batched
    from sherman_tpu.models.validate import check_structure_device
    from sherman_tpu.multihost import HostRouter, MultihostService
    from sherman_tpu.recovery import RecoveryPlane
    from sherman_tpu.serve import (RetryingClient, RetryPolicy,
                                   ServeConfig, ShermanServer)
    from sherman_tpu.utils import journal as J

    t_start = time.time()
    H = 2
    out: dict = {"metric": "hostfail_drill", "seed": a.seed, "ok": False,
                 "hosts": H, "keys": a.keys, "lease_s": a.lease_s}
    root = a.dir or tempfile.mkdtemp(prefix="sherman_hostfail_")
    out["dir"] = root
    snap0 = obs.snapshot()

    # -- phase 1: two host contexts + the lease table -------------------------
    router = HostRouter(H)
    rng = np.random.default_rng(3)
    keys = np.unique(rng.integers(1, 1 << 56, int(a.keys * 1.05),
                                  dtype=np.uint64))[:a.keys]
    vals = keys ^ np.uint64(SALT)
    own = router.owner(keys)
    out["key_split"] = [int((own == h).sum()) for h in range(H)]
    assert all(n > 0 for n in out["key_split"]), "degenerate key split"

    hc = HostChaos([], seed=a.seed)
    table = HostLeaseTable(root, H, lease_s=a.lease_s, chaos=hc)

    widths = (256, 1024)
    big = {c: 1e9 for c in ("read", "scan", "insert", "delete")}

    def front_door(engine, host_id: int, calib: np.ndarray):
        cfg = ServeConfig(widths=widths, p99_targets_ms=dict(big),
                          write_linger_ms=0.5, write_width=2048,
                          group_commit_ms=2.0)
        srv = ShermanServer(engine, cfg, host_id=host_id)
        ck = calib[:256]
        cv, cf = engine.search(ck)
        srv.start(calib_keys=calib,
                  calib_writes=(ck[cf], np.asarray(cv)[cf]),
                  calib_delete_keys=np.asarray([1 << 60], np.uint64))
        return srv

    ppn = pages_for_keys(a.keys)
    hosts = []  # [(cluster, tree, eng, plane, srv, my_keys)]
    epochs = {}
    for h in range(H):
        cluster, tree, eng = build_cluster(
            1, ppn, batch_per_node=512,
            locks_per_node=1024, chunk_pages=64)
        my = keys[own == h]
        batched.bulk_load(tree, my, my ^ np.uint64(SALT))
        eng.attach_router()
        check_structure_device(tree)
        plane = RecoveryPlane(cluster, tree, eng, root,
                              group_commit_ms=2.0, host_id=h, hosts=H)
        plane.checkpoint_base()
        epochs[h] = table.register(
            h, hwm=(eng.journal.path, os.path.getsize(eng.journal.path)))
        HostFence(table, h, epochs[h], chaos=hc).install(eng)
        srv = front_door(eng, h, my)
        hosts.append((cluster, tree, eng, plane, srv, my))
    svc = MultihostService([x[4] for x in hosts], router,
                           planes=[x[3] for x in hosts])
    svc.attach_chaos(hc)
    failover = HostFailover(root, table, H,
                            recover_kw={"group_commit_ms": 2.0})

    # the renewer: each host's heartbeat, gated by chaos (a frozen or
    # zombified host's renewals are suppressed at the seam)
    stop_renew = threading.Event()

    def renewer():
        while not stop_renew.is_set():
            for h in range(H):
                table.renew(h, epochs[h])
            time.sleep(a.lease_s / 5.0)

    renew_thr = threading.Thread(target=renewer, daemon=True)
    renew_thr.start()

    # -- acked mixed traffic through the routed front door --------------------
    n_writers, n_readers = 2, 1
    per = a.keys // (n_writers + 2)
    del_slice = keys[n_writers * per:(n_writers + 1) * per]
    acked: list[dict] = [dict() for _ in range(n_writers + 1)]
    unacked: list[dict] = [dict() for _ in range(n_writers + 1)]
    events: list[list] = [[] for _ in range(n_writers + 1 + n_readers)]
    stop = threading.Event()
    gens = [0] * n_writers
    pol = RetryPolicy(max_attempts=6, hedge_reads=False)

    def writer(w: int, n_reqs: int):
        my = keys[w * per:(w + 1) * per]
        cl = RetryingClient(svc, tenant=f"writer{w}", policy=pol,
                            seed=100 + w + gens[w])
        ev = events[w]
        wrng = np.random.default_rng(1000 * w + gens[w])
        done = 0
        while not stop.is_set() and (n_reqs == 0 or done < n_reqs):
            gens[w] += 1
            done += 1
            time.sleep(0.005)
            kreq = np.unique(my[wrng.integers(0, my.size, 48)])
            vreq = kreq ^ np.uint64(SALT) ^ np.uint64(gens[w] << 8)
            t_inv = time.perf_counter()
            try:
                ok = cl.insert(kreq, vreq)
            except ShermanError:
                # in flight across the outage: result unknown, not owed
                for k, v in zip(kreq.tolist(), vreq.tolist()):
                    unacked[w].setdefault(k, []).append((True, v))
                continue
            t_resp = time.perf_counter()
            for k, v, o in zip(kreq.tolist(), vreq.tolist(),
                               ok.tolist()):
                if o:
                    acked[w][k] = (True, v)
                    ev.append((k, A.OP_INSERT, t_inv, t_resp, v, True))

    def deleter(n_reqs: int):
        cl = RetryingClient(svc, tenant="deleter", policy=pol, seed=300)
        ev = events[n_writers]
        drng = np.random.default_rng(4000)
        done = 0
        while not stop.is_set() and (n_reqs == 0 or done < n_reqs):
            done += 1
            time.sleep(0.011)
            kreq = np.unique(
                del_slice[drng.integers(0, del_slice.size, 24)])
            t_inv = time.perf_counter()
            try:
                found = cl.delete(kreq)
            except ShermanError:
                for k in kreq.tolist():
                    unacked[n_writers].setdefault(k, []).append(
                        (False, None))
                continue
            t_resp = time.perf_counter()
            for k, f in zip(kreq.tolist(), found.tolist()):
                acked[n_writers][k] = (False, None)
                ev.append((k, A.OP_DELETE, t_inv, t_resp, None,
                           bool(f)))

    def reader(r: int):
        cl = RetryingClient(svc, tenant=f"reader{r}", policy=pol,
                            seed=200 + r, deadline_ms=5000.0)
        ev = events[n_writers + 1 + r]
        rrng = np.random.default_rng(50 + r)
        while not stop.is_set():
            kreq = np.unique(keys[rrng.integers(0, keys.size, 64)])
            t_inv = time.perf_counter()
            try:
                got, found = cl.read(kreq)
            except ShermanError:
                continue
            t_resp = time.perf_counter()
            for k, g, f in zip(kreq.tolist(), got.tolist(),
                               found.tolist()):
                ev.append((k, A.OP_READ, t_inv, t_resp,
                           g if f else None, bool(f)))
            time.sleep(0.001)

    readers = [threading.Thread(target=reader, args=(r,), daemon=True)
               for r in range(n_readers)]
    for t in readers:
        t.start()
    n_round = max(4, int(a.secs * 5))

    def run_round(n_reqs: int):
        ws = [threading.Thread(target=writer, args=(w, n_reqs),
                               daemon=True) for w in range(n_writers)]
        ws.append(threading.Thread(target=deleter, args=(n_reqs,),
                                   daemon=True))
        for t in ws:
            t.start()
        return ws

    # round 1: acked load, both hosts up
    for t in run_round(n_round):
        t.join(timeout=300)

    # the exactly-once probe: one acked rid whose result must re-ack
    # IDENTICALLY through the adopter after host 0 dies
    prng = np.random.default_rng(77)
    h0keys = keys[own == 0]
    imm = keys[(n_writers + 1) * per:]  # no writer/deleter slice
    h0imm = imm[router.owner(imm) == 0]
    pk = np.unique(h0imm[prng.integers(0, h0imm.size, 32)])
    pv = pk ^ np.uint64(SALT) ^ np.uint64(0xBEEF << 16)
    probe_f = svc.submit("insert", pk, pv, tenant="probe",
                         rid=PROBE_RID)
    probe_ok = np.asarray(probe_f.result(timeout=120), bool)
    assert probe_ok.all()
    t_inv = time.perf_counter()
    for k, v in zip(pk.tolist(), pv.tolist()):
        acked[0][k] = (True, v)
        events[0].append((k, A.OP_INSERT, t_inv, t_inv, v, True))

    # round 2: open-ended — traffic KEEPS RUNNING through the failure
    ws = run_round(0)
    time.sleep(min(0.5, a.secs / 4))

    # -- freeze: host 0 stops responding AND stops heartbeating ---------------
    t_freeze = time.perf_counter()
    hc.freeze(0)
    # the frozen process serves nothing: its door's dispatcher stops
    # dead (no drain, journal left open — the crash image), queued
    # requests fail typed and the clients ledger them as unacked
    hosts[0][4].kill()
    # one in-flight append inside the frozen host pins its lease view:
    # the host was mid-write when it froze, and from here on it cannot
    # watch its own epoch get bumped (PIN key sits outside the client
    # keyspace — it replays as pre-fence durable state, never read)
    eng0 = hosts[0][2]
    eng0.journal.append(J.J_UPSERT, np.asarray([1 << 58], np.uint64),
                        np.asarray([1], np.uint64))
    # crash image: torn half-frame (in-flight at the freeze, unacked)
    rec = J.encode_record(J.J_UPSERT, np.asarray([1 << 40], np.uint64),
                          np.asarray([7], np.uint64), rid=0xDEAD)
    with open(hosts[0][2].journal.path, "ab") as f:
        f.write(rec[: len(rec) // 2])

    # the lease expires UNDER TRAFFIC (the renewer is still stamping
    # host 1; host 0's renewals are chaos-suppressed)
    deadline = time.time() + max(20.0, 40 * a.lease_s)
    while failover.detect() != [0] and time.time() < deadline:
        time.sleep(a.lease_s / 10.0)
    assert failover.detect() == [0], "host 0's lease never expired"
    assert failover.unadopted_dead_hosts() == 1
    t_expired = time.perf_counter()
    out["detect_ms"] = round((t_expired - t_freeze) * 1e3, 1)

    # -- adoption: host 1 takes over host 0's namespace -----------------------
    def door(plane, cluster, tree, eng):
        return front_door(eng, 1, h0keys)

    r = failover.adopt(0, 1, door_factory=door, service=svc)
    assert r["seeded"] > 0, "dead dedup window did not re-seed"
    assert r["fence"] is not None
    out["adoption"] = {"dead": r["dead"], "adopter": r["adopter"],
                       "epoch": r["epoch"], "seeded": r["seeded"],
                       "fence": r["fence"],
                       "adoption_ms": r["adoption_ms"]}
    # first successful routed op on the DEAD keyspace closes the gap
    avail_deadline = time.time() + 60
    while True:
        try:
            g, f = svc.submit("read", pk).result(timeout=30)
            break
        except ShermanError:
            assert time.time() < avail_deadline, "keyspace never returned"
            time.sleep(0.01)
    t_avail = time.perf_counter()
    out["availability_gap_ms"] = round((t_avail - t_freeze) * 1e3, 1)
    assert np.asarray(f, bool).all()
    np.testing.assert_array_equal(np.asarray(g, np.uint64), pv)

    # -- zombie: host 0 revives with its PINNED pre-bump lease view -----------
    hc.revive(0, zombie=True)
    fenced_pairs = []
    zrng = np.random.default_rng(a.seed)
    for i in range(3):
        zk = np.unique(h0keys[zrng.integers(0, h0keys.size, 8)])
        zv = zk ^ np.uint64(0xFEFE << 8) ^ np.uint64(i)
        # a stale ack: the zombie's own durability gate still says
        # epoch 1, so the append LANDS — past the fence point
        eng0.journal.append(J.J_UPSERT, zk, zv, rid=0xF0 + i)
        fenced_pairs += list(zip(zk.tolist(), zv.tolist()))
    suffix = count_fenced_suffix((os.path.join(root,
                                               r["fence"]["segment"]),
                                  r["fence"]["size"]))
    out["fenced_suffix_frames"] = suffix
    assert suffix >= 3, f"zombie appends not past the fence: {suffix}"
    # heal: the epoch bump becomes visible — the NEXT stale ack is a
    # typed refusal at the durability gate
    hc.heal()
    typed = 0
    try:
        eng0.journal.append(J.J_UPSERT, np.asarray([h0keys[0]],
                                                   np.uint64),
                            np.asarray([0], np.uint64))
    except StaleHostError:
        typed = 1
    out["zombie_typed_rejections"] = typed
    assert typed == 1, "post-heal zombie append was not typed-fenced"

    # the retried probe rid re-acks its ORIGINAL result through the
    # adopter's re-seeded window — exactly-once across host death
    f2 = svc.submit("insert", pk, pv, tenant="probe", rid=PROBE_RID)
    re_ok = np.asarray(f2.result(timeout=120), bool)
    dup = 0 if (bool(f2.deduped)
                and np.array_equal(re_ok, probe_ok)) else 1
    out["duplicate_acks"] = dup
    assert dup == 0, "retried rid did not dedup through the adopter"

    # -- stop traffic, audit --------------------------------------------------
    stop.set()
    for t in ws + readers:
        t.join(timeout=120)
    svc_stats = svc.stats()
    assert svc_stats["adoptions"] == 1
    assert svc_stats["overlay"] == {"0": 1}

    # fenced acks provably never merged: read every fenced (key, value)
    # pair back through the ADOPTED door
    fa = A.check_fenced_rejected(
        lambda ks: _chunked_svc_read(svc, ks), fenced_pairs)
    out["fenced_acks"] = fa["fenced"]
    out["fenced_acks_merged"] = fa["merged"]
    assert fa["merged"] == 0, \
        f"zombie acks merged: {fa['violations'][:3]}"

    # lost acks: the merged acked-op ledger against the adopted plane
    merged: dict = {}
    for d in acked:
        merged.update(d)
    assert merged, "drill acked no ops"
    assert any(not pres for pres, _ in merged.values()), \
        "drill acked no deletes (mixed traffic pin)"
    open_w: dict = {}
    for d in unacked:
        for k, outs in d.items():
            open_w.setdefault(k, []).extend(outs)
    ak = np.asarray(sorted(merged), np.uint64)
    t_inv = time.perf_counter()
    got, found = _chunked_svc_read(svc, ak)
    t_resp = time.perf_counter()
    # an acked op's result must be served — unless a LATER in-flight
    # (result-unknown) write on the same key could have replaced it:
    # per key, the observed state must match the last acked outcome
    # or one of the open-write outcomes (same-thread program order)
    lost = 0
    lost_keys = []
    for k, g, f in zip(ak.tolist(), got.tolist(), found.tolist()):
        seen = (bool(f), int(g) if f else None)
        allowed = [merged[k]] + open_w.get(k, [])
        if not any(pres == seen[0] and (not pres or int(v) == seen[1])
                   for pres, v in allowed):
            lost += 1
            lost_keys.append((k, merged[k], seen))
    post_events = [(int(k), A.OP_READ, t_inv, t_resp,
                    int(g) if f else None, bool(f))
                   for k, g, f in zip(ak.tolist(), got.tolist(),
                                      found.tolist())]
    # untouched-key probe: bulk values still served verbatim.  A key
    # with an in-flight write at the kill is NOT untouched: its
    # host-1 sub-batch may have applied before the merged future
    # failed (result unknown, ledgered as an open write for the
    # audit) — exclude those too
    touched = set(merged)
    for d in unacked:
        touched.update(d)
    tk = np.asarray(sorted(touched), np.uint64)
    probe = keys[~np.isin(keys, tk)][:: max(1, a.keys // 512)]
    got, found = _chunked_svc_read(svc, probe)
    lost += int((~found).sum()) + int(
        (got[found] != (probe ^ np.uint64(SALT))[found]).sum())
    out["lost_acks"] = lost
    assert lost == 0, \
        f"{lost} acked/bulk ops lost across adoption: {lost_keys[:3]}"

    # nothing left dead: host 0 is adopted, host 1 is still renewing
    out["unadopted_dead_hosts"] = failover.unadopted_dead_hosts()
    assert out["unadopted_dead_hosts"] == 0
    stop_renew.set()
    renew_thr.join(timeout=30)

    # offline linearizability over the WHOLE routed history
    all_events = [e for ev in events for e in ev] + post_events
    initial = {int(k): (True, int(v)) for k, v in zip(keys, vals)}
    verdict = A.check_events(all_events, initial=initial,
                             open_writes=open_w)
    out["audit"] = {
        "events": verdict["events"], "keys": verdict["keys"],
        "reads_checked": verdict["reads"],
        "violations": len(verdict["violations"]),
        "linearizable": bool(verdict["linearizable"]),
    }
    out["linearizable"] = bool(verdict["linearizable"])
    if verdict["violations"]:
        out["audit"]["first_violations"] = verdict["violations"][:3]
    assert verdict["linearizable"], \
        f"history not linearizable: {verdict['violations'][:3]}"
    assert verdict["reads"] > 0, "audit checked no reads"
    jsonl = os.path.join(root, "history.jsonl")
    A.dump_jsonl(all_events, jsonl)
    out["history_jsonl"] = jsonl

    out["service"] = {
        "admitted_ops": svc_stats["admitted_ops"],
        "served_ops": svc_stats["served_ops"],
        "acked_writes": svc_stats["acked_writes"],
        "adoptions": svc_stats["adoptions"],
        "overlay": svc_stats["overlay"],
    }
    assert svc_stats["acked_writes"] > 0

    # flight-event + collector pins
    kinds = {e["kind"] for e in obs.get_recorder().events()}
    for want in ("host.lease_expired", "host.adopt_begin",
                 "host.adopt_done", "host.zombie_fenced"):
        assert want in kinds, f"missing flight event {want}"
    d = obs.delta(snap0, obs.snapshot())
    out["obs"] = {k: round(float(d[k]), 2) for k in sorted(d)
                  if k.startswith(("hostfail.", "multihost.adoptions",
                                   "chaos.host"))}
    assert d.get("hostfail.expirations", 0) >= 1
    assert d.get("hostfail.adoptions", 0) == 1
    assert d.get("hostfail.fenced_host_acks", 0) >= 1

    r["server"].stop()
    for _cl, _tr, _en, pl, srv, _my in hosts:
        try:
            srv.kill()
        except Exception:
            pass
        pl.close()
    r["context"][0].close()
    out["elapsed_s"] = round(time.time() - t_start, 1)
    out["ok"] = True
    line = json.dumps(out)
    print(line)
    receipt = os.environ.get("SHERMAN_HOSTFAIL_RECEIPT")
    if receipt:
        with open(receipt, "w") as f:
            f.write(line + "\n")
    print("HOSTFAIL-DRILL PASS", file=sys.stderr)
    return out


if __name__ == "__main__":
    main()
