#!/usr/bin/env python
"""Native skiplist micro-bench — ``test/skiplist_test.cpp`` parity.

The only host-only unit test in the reference: insert 100K keys into the
concurrent skiplist, then time 10K seeks (``skiplist_test.cpp:54-95``).
Exercises the native library's SkipList (the IndexCache's ordered core).

    python tools/skiplist_test.py [--inserts N] [--seeks N]
"""

from __future__ import annotations

import argparse

import numpy as np

import common  # noqa: F401  (repo-root sys.path bootstrap)


def main(argv=None) -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--inserts", type=int, default=100_000)
    p.add_argument("--seeks", type=int, default=10_000)
    a = p.parse_args(argv)

    from sherman_tpu import native
    from sherman_tpu.utils import Timer

    if not native.available():
        print(f"native library unavailable: {native.load_error()}")
        raise SystemExit(1)

    sl = native.SkipList(a.inserts + 16)
    rng = np.random.default_rng(5)
    keys = rng.permutation(np.arange(1, a.inserts + 1, dtype=np.uint64))

    t = Timer()
    t.begin()
    for k in keys:
        sl.insert(int(k), int(k) * 2)
    ins_ns = t.end(a.inserts)
    assert len(sl) == a.inserts

    probe = rng.integers(1, a.inserts, a.seeks, dtype=np.uint64)
    t.begin()
    for k in probe:
        kv = sl.seek_ge(int(k))
        assert kv is not None and kv[0] >= int(k)
    seek_ns = t.end(a.seeks)

    # correctness spot check: seek_ge returns the exact key when present
    for k in (1, a.inserts // 2, a.inserts):
        kv = sl.seek_ge(k)
        assert kv == (k, k * 2), kv

    print(f"skiplist: insert {ins_ns:.0f} ns/op, seek_ge {seek_ns:.0f} ns/op "
          f"({a.inserts} inserts, {a.seeks} seeks)")
    print("skiplist_test PASS")


if __name__ == "__main__":
    main()
