#!/usr/bin/env python
"""YCSB A-F matrix driver (``bench.py --ycsb``).

Runs the full core-workload matrix (``sherman_tpu/workload/ycsb.py``)
as first-class bench rows over one bulk-loaded tree:

- **A/B** (zipf read/update) ride the fused ``mixed`` step inline, or
  the value heap's get/put paths with variable-length payloads;
- **C** (zipf read-only) is the headline row: with the heap ON, every
  read resolves its payload in the fused descent fan-out + heap gather
  program, with the gather phase attributed separately (``phase_ms``:
  ``read_fanout`` vs ``heap_gather``, chained-delta) and the loop runs
  SEALED (compile ledger; ``retraces`` published, pinned 0 in CI);
- **D** (read-latest + inserts) advances the insert frontier;
- **E** (scans + inserts) drives ``range_query_many`` — with the heap
  ON every scan hit's payload is gathered in one resolve step;
- **F** (read-modify-write) re-reads then re-stamps.

Every row publishes its ANALYTIC twin (op-class mix by construction,
expected rows per scan in the hashed keyspace) next to the measured
number, plus a sampled AUDIT against the host reference resolver when
the heap is on (device payloads must be bit-identical).

``--ab`` additionally runs the YCSB-C heap-on vs inline A/B at two
value size classes — the "what does out-of-line cost on reads" receipt.

The receipt's ``config`` block carries ``value_bytes``/``value_dist``/
``value_heap`` — perfgate treats rows with differing value config as
incomparable (the ``nodes`` rule's pattern).

Run::

    python tools/ycsb_bench.py [--keys 200000] [--ops 8192] [--steps 8]
        [--theta 0.99] [--workloads A,B,C,D,E,F] [--value-bytes 64]
        [--value-dist fixed] [--nodes 1] [--ab]

Env twins (the README knob table): ``SHERMAN_YCSB_OPS``,
``SHERMAN_YCSB_WORKLOADS``, ``SHERMAN_VALUE_BYTES``,
``SHERMAN_VALUE_DIST``; ``SHERMAN_VALUE_HEAP`` sizes the heap region
(0 = inline values).  Prints ONE JSON line (``metric: ycsb_matrix``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from common import pages_for_keys, setup_platform  # noqa: E402

SALT = 0x5E17_AB1E_5A17


def build(n_keys: int, ops: int, nodes: int, heap_pages: int,
          value_bytes: int, value_dist: str):
    """Cluster + bulk-loaded tree + engine (+ heap migration)."""
    from sherman_tpu.cluster import Cluster
    from sherman_tpu.config import DSMConfig, TreeConfig
    from sherman_tpu.models import batched
    from sherman_tpu.models.btree import Tree
    from sherman_tpu.ops import bits
    from sherman_tpu.workload.ycsb import payload_for_key

    ranks = np.arange(n_keys, dtype=np.uint64)
    keys = np.unique(bits.mix64_np(ranks ^ np.uint64(SALT)))
    vals = keys ^ np.uint64(0xD00D)
    # D/E grow the frontier ~5% of the op budget: size the pool for it
    grow = max(1024, ops * 64 // 8)
    cfg = DSMConfig(
        machine_nr=nodes,
        pages_per_node=pages_for_keys((n_keys + grow) // nodes + 1),
        locks_per_node=16384,
        step_capacity=max(512, min(ops, 8192)),
        chunk_pages=256,
        heap_pages_per_node=heap_pages)
    cluster = Cluster(cfg)
    tree = Tree(cluster)
    batched.bulk_load(tree, keys, vals)
    eng = batched.BatchedEngine(
        tree, batch_per_node=max(256, -(-ops // nodes)),
        tcfg=TreeConfig(sibling_chase_budget=2))
    eng.attach_router()
    vh = None
    if heap_pages:
        vh = eng.attach_value_heap()
        # migrate the loaded records out of line (chunked puts)
        step = max(1024, ops)
        for i in range(0, keys.size, step):
            ck = keys[i: i + step]
            vh.put(ck, [payload_for_key(int(k), value_bytes, value_dist)
                        for k in ck])
    return cluster, tree, eng, vh, keys


def _percentiles(walls_ms):
    w = np.sort(np.asarray(walls_ms))
    if w.size == 0:
        return 0.0, 0.0
    return (float(w[int(0.5 * (w.size - 1))]),
            float(w[int(np.ceil(0.99 * (w.size - 1)))]))


def run_workload(eng, vh, gen, *, ops: int, steps: int,
                 seal: bool = False) -> dict:
    """Closed-loop ``steps`` batches of ``ops`` ops.  One warmup batch
    compiles every shape, then (optionally) the compile ledger seals
    around the timed loop — a retrace in steady state is a counted
    hazard, not a mystery."""
    from sherman_tpu.obs import device as DEV
    from sherman_tpu.workload.ycsb import payload_for_key

    counts = {"read": 0, "update": 0, "insert": 0, "rmw": 0,
              "scan": 0, "scan_rows": 0, "scan_rows_expected": 0}

    def play(b) -> None:
        heap = vh is not None
        rk = b.get("read")
        uk = b.get("update")
        if not heap and rk is not None and uk is not None \
                and "scan" not in b and "rmw" not in b:
            # the fused mixed step serves the whole read+update batch
            keys = np.concatenate([rk, uk])
            isr = np.zeros(keys.size, bool)
            isr[: rk.size] = True
            eng.mixed(keys, keys ^ np.uint64(0xBEEF), isr)
            counts["read"] += rk.size
            counts["update"] += uk.size
            rk = uk = None
        if rk is not None:
            (vh.get(rk) if heap else eng.search_combined(rk))
            counts["read"] += rk.size
        if uk is not None:
            if heap:
                vh.put(uk, [payload_for_key(int(k) ^ 1, gen.value_bytes,
                                            gen.value_dist)
                            for k in uk])
            else:
                eng.insert(uk, uk ^ np.uint64(0xBEEF))
            counts["update"] += uk.size
        ik = b.get("insert")
        if ik is not None:
            if heap:
                vh.put(ik, gen.payloads_for_keys(ik))
            else:
                eng.insert(ik, ik ^ np.uint64(0xD00D))
            counts["insert"] += ik.size
        fk = b.get("rmw")
        if fk is not None:
            if heap:
                got, fnd = vh.get(fk)
                vh.put(fk, [(g or b"\x00") + b"!"
                            if len(g or b"") < gen.value_bytes
                            else (g or b"\x00")
                            for g in got])
            else:
                v, fnd = eng.search_combined(fk)
                eng.insert(fk, v ^ np.uint64(1))
            counts["rmw"] += fk.size
        sc = b.get("scan")
        if sc is not None:
            res = vh.scan(sc) if heap else eng.range_query_many(sc)
            counts["scan"] += len(sc)
            counts["scan_rows"] += int(sum(len(r[0]) for r in res))
            counts["scan_rows_expected"] += int(
                b.get("scan_expected_rows", 0))

    play(gen.batch(ops))  # warmup: compile every class's shapes
    ledger = DEV.get_ledger()
    r0 = ledger.retraces
    if seal:
        ledger.seal()
    walls = []
    t0 = time.perf_counter()
    try:
        for _ in range(steps):
            ts = time.perf_counter()
            play(gen.batch(ops))
            walls.append((time.perf_counter() - ts) * 1e3)
    finally:
        if seal:
            ledger.unseal()
    total_s = time.perf_counter() - t0
    p50, p99 = _percentiles(walls)
    out = {
        "ops": ops * steps,
        "ops_s": round(ops * steps / total_s),
        "step_p50_ms": round(p50, 2),
        "step_p99_ms": round(p99, 2),
        "counts": {k: int(v) for k, v in counts.items() if v},
        "analytic": gen.expectations(),
        "sealed": bool(seal),
        "retraces": int(ledger.retraces - r0),
    }
    if counts["scan"]:
        out["scan_rows_per_scan"] = round(
            counts["scan_rows"] / counts["scan"], 2)
        out["scan_rows_expected_per_scan"] = round(
            counts["scan_rows_expected"] / counts["scan"], 2)
    return out


def heap_phase_attribution(eng, vh, keys, ops: int, reps: int = 4) -> dict:
    """Chained-delta attribution of the heap READ path: the descent
    fan-out alone vs fan-out + heap gather (the extra phase's cost),
    plus the standalone resolve program — the receipt's proof that the
    payload gather rides the fused step instead of a second descent."""
    import jax
    rng = np.random.default_rng(3)
    kb = keys[rng.integers(0, keys.size, ops)]

    def t(fn):
        fn()  # warm
        t0 = time.perf_counter()
        for _ in range(reps):
            fn()
        jax.block_until_ready(eng.dsm.pool)
        return (time.perf_counter() - t0) / reps * 1e3

    fanout_ms = t(lambda: eng.search_combined(kb))
    fused_ms = t(lambda: vh.get(kb))
    vals, found = eng.search_combined(kb)
    resolve_ms = t(lambda: vh.resolve_u64(vals, found))
    return {
        "read_fanout_ms": round(fanout_ms, 2),
        "fused_read_ms": round(fused_ms, 2),
        "heap_gather_ms": round(resolve_ms, 2),
        "fused_overhead_ms": round(fused_ms - fanout_ms, 2),
    }


def audit_heap(eng, vh, keys, n: int = 256) -> bool:
    """Sampled device-vs-host-reference bit-identity audit."""
    rng = np.random.default_rng(11)
    ks = keys[rng.integers(0, keys.size, n)]
    dev, found = vh.get(ks)
    vals, f2 = eng.search(ks)
    ref, ok = vh.resolve_host(vals, f2)
    for i in range(ks.size):
        if bool(found[i]) != bool(f2[i] and ok[i]):
            return False
        if found[i] and dev[i] != ref[i]:
            return False
    return True


def main(argv=None) -> dict:
    ap = argparse.ArgumentParser(description="YCSB A-F matrix bench")
    ap.add_argument("--keys", type=int, default=int(os.environ.get(
        "SHERMAN_BENCH_KEYS", 200_000)))
    ap.add_argument("--ops", type=int, default=int(os.environ.get(
        "SHERMAN_YCSB_OPS", 8192)), help="ops per closed-loop step")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--theta", type=float, default=0.99)
    ap.add_argument("--workloads", default=os.environ.get(
        "SHERMAN_YCSB_WORKLOADS", "A,B,C,D,E,F"))
    ap.add_argument("--value-bytes", type=int, default=int(os.environ.get(
        "SHERMAN_VALUE_BYTES", 64)))
    ap.add_argument("--value-dist", default=os.environ.get(
        "SHERMAN_VALUE_DIST", "fixed"), choices=("fixed", "uniform"))
    ap.add_argument("--nodes", type=int, default=1)
    ap.add_argument("--ab", action="store_true",
                    help="YCSB-C heap-on vs inline A/B at 2 size classes")
    a = ap.parse_args(argv)

    setup_platform(a.nodes)
    from sherman_tpu.config import value_heap_pages
    from sherman_tpu.workload.ycsb import YcsbGen

    heap_pages = value_heap_pages()
    cluster, tree, eng, vh, keys = build(
        a.keys, a.ops, a.nodes, heap_pages, a.value_bytes, a.value_dist)

    rows = {}
    for w in [w.strip().upper() for w in a.workloads.split(",")
              if w.strip()]:
        gen = YcsbGen(w, a.keys, theta=a.theta, seed=17, salt=SALT,
                      value_bytes=a.value_bytes,
                      value_dist=a.value_dist)
        rows[w] = run_workload(eng, vh, gen, ops=a.ops, steps=a.steps,
                               seal=(w == "C"))
        print(f"# YCSB-{w}: {rows[w]['ops_s']:,} ops/s "
              f"(p99 {rows[w]['step_p99_ms']} ms/step)",
              file=sys.stderr)

    out = {
        "metric": "ycsb_matrix",
        "schema_version": 3,
        "keys": a.keys,
        "batch": a.ops,
        "nodes": a.nodes,
        "theta": a.theta,
        "workloads": rows,
        "config": {
            "gather_impl": cluster.cfg.gather_impl,
            "exchange_impl": cluster.cfg.exchange_impl,
            "value_bytes": a.value_bytes if heap_pages else 8,
            "value_dist": a.value_dist if heap_pages else "fixed",
            "value_heap": bool(heap_pages),
        },
    }
    if vh is not None:
        out["heap"] = vh.stats()
        out["heap_phase_ms"] = heap_phase_attribution(eng, vh, keys,
                                                      a.ops)
        out["audit_ok"] = audit_heap(eng, vh, keys)
    if a.ab and heap_pages:
        out["ycsb_c_ab"] = run_c_ab(a)
    print(json.dumps(out))
    return out


def run_c_ab(a) -> dict:
    """YCSB-C heap-on vs inline at two value size classes: fresh
    engines per arm (arms must not share compiled-shape warmth or
    pool state)."""
    from sherman_tpu.models import value_heap as VH
    from sherman_tpu.workload.ycsb import YcsbGen
    arms = {}
    for label, vb in (("inline", 8), ("heap_28B", 28),
                      ("heap_252B", 252)):
        heap_pages = 0
        if label != "inline":
            cls = VH.class_for_bytes(vb)
            slabs = VH.SLAB_REGION_WORDS // VH.HEAP_CLASSES[cls]
            heap_pages = (a.keys // slabs // max(1, a.nodes)
                          + a.keys // slabs // 8 + 64)
        _, _, eng2, vh2, _ = build(a.keys, a.ops, a.nodes, heap_pages,
                                   vb, "fixed")
        gen = YcsbGen("C", a.keys, theta=a.theta, seed=17, salt=SALT,
                      value_bytes=vb, value_dist="fixed")
        arms[label] = run_workload(eng2, vh2, gen, ops=a.ops,
                                   steps=a.steps, seal=True)
        arms[label]["value_bytes"] = vb
        print(f"# YCSB-C A/B {label}: {arms[label]['ops_s']:,} ops/s",
              file=sys.stderr)
    return arms


if __name__ == "__main__":
    main()
