#!/usr/bin/env python
"""YCSB-style benchmark driver — ``test/benchmark.cpp`` parity.

CLI contract (benchmark.cpp:193-205):

    python tools/benchmark.py <kNodeCount> <kReadRatio> <kThreadCount>
        [--keys N] [--theta T] [--secs S] [--ops-per-coro N] [--windows W]

- ``kNodeCount``   — cluster nodes (mesh size; 1 = the real chip, >1 runs
  on a virtual CPU mesh when the hardware doesn't have that many chips).
- ``kReadRatio``   — percent of operations that are searches (YCSB-C=100,
  YCSB-B=95, YCSB-A=50); the rest are upserts.
- ``kThreadCount`` — client threads per node.  The reference keeps
  kThreadCount x kCoroCnt ops in flight per node (``Tree.cpp:1059-1122``);
  the batched engine realizes the same concurrency as one step of
  B = kThreadCount x kCoroCnt x opsPerCoro keys.

Workload (benchmark.cpp:15-24,159-188): keyspace of --keys unique keys,
warm ratio 0.8 bulk-loaded, zipf(--theta) sampling over the warm set.
Reports per 2-second window: per-node + cluster throughput (via
keeper.sum, DSMKeeper.cpp:163-176), reads/op, and every 3rd window the
p50/p90/p95/p99/p999 op latency from the native 0.1 us histogram
(cal_latency, benchmark.cpp:207-249).  In the batched execution model a
key's completion latency IS its step's latency, so each step records
(span, batch) into the histogram.
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from common import build_cluster, pages_for_keys, setup_platform

KCORO = 8          # kCoroCnt (Common.h:62-71)
WARM_RATIO = 0.8   # kWarmRatio (benchmark.cpp:19)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("kNodeCount", type=int)
    p.add_argument("kReadRatio", type=int)
    p.add_argument("kThreadCount", type=int)
    p.add_argument("--keys", type=int, default=1_000_000)
    p.add_argument("--theta", type=float, default=0.99)
    p.add_argument("--secs", type=float, default=10.0)
    p.add_argument("--ops-per-coro", type=int, default=64,
                   help="batched ops per (thread, coroutine) slot")
    p.add_argument("--window", type=float, default=2.0,
                   help="report window seconds (benchmark.cpp:300)")
    p.add_argument("--combine", choices=("auto", "on", "off"),
                   default="auto",
                   help="read-request combining: duplicate lookups in a "
                        "batch share one descent (auto: on for read-only "
                        "skewed workloads)")
    p.add_argument("--scans", type=int, default=0,
                   help="range scans per report window (the multi-node "
                        "mixed + range-scan config: exercises sibling-link "
                        "traversal, Tree.cpp:461-522)")
    p.add_argument("--scan-span", type=int, default=1000,
                   help="target entries per range scan")
    p.add_argument("--exchange", choices=("xla", "pallas"), default="xla",
                   help="data-plane exchange implementation. 'pallas' = "
                        "explicit one-sided remote-DMA writes per peer "
                        "(the Operation.cpp:351-481 analogue, "
                        "parallel/transport_pallas.py): compiled on "
                        "multi-chip TPU meshes, interpreter-mode on CPU "
                        "meshes.  Before the benchmark it runs the "
                        "engine drill on BOTH impls and diffs the DSM op "
                        "counters (must match exactly).  Auto-skips "
                        "(exit 0, one JSON line) when the mesh has one "
                        "device — the first-pod checklist command, see "
                        "PARITY.md")
    p.add_argument("--preempt-ckpt", default=None, metavar="PATH",
                   help="graceful preemption: on SIGTERM (single process) "
                        "or a cluster preemption notice (multihost sync "
                        "manager), checkpoint the cluster to PATH at the "
                        "next block boundary and stop "
                        "(utils.failure.PreemptionGuard)")
    return p.parse_args(argv)


def exchange_counter_diff(n_nodes: int) -> dict:
    """Certify the pallas one-sided exchange against the default XLA
    all_to_all: run the SAME deterministic engine drill (insert with
    device splits, routed search, delete, re-search) on two fresh
    clusters that differ ONLY in ``exchange_impl``, then diff their DSM
    op counters.  The transport must be semantically invisible: any
    counter divergence means the remote-DMA path dropped, duplicated, or
    re-routed a request.  Returns {"xla": snap, "pallas": snap,
    "diff": {counter: pallas - xla}} — the first-pod turnkey check
    (VERDICT: pre-wire the compiled Pallas run)."""
    snaps = {}
    for impl in ("xla", "pallas"):
        cluster, tree, eng = build_cluster(n_nodes, 4096, 128,
                                           exchange_impl=impl)
        rng = np.random.default_rng(42)
        keys = np.unique(rng.integers(1, 1 << 48, 512, dtype=np.uint64))
        vals = keys ^ np.uint64(0xABCD)
        eng.insert(keys, vals)
        eng.attach_router()
        got, found = eng.search(keys)
        assert found.all() and (got == vals).all(), \
            f"exchange={impl}: engine drill lost keys"
        eng.delete(keys[::3])
        _, f2 = eng.search(keys[::3])
        assert not f2.any(), f"exchange={impl}: delete drill failed"
        snaps[impl] = dict(cluster.dsm.counter_snapshot())
    diff = {k: snaps["pallas"].get(k, 0) - snaps["xla"].get(k, 0)
            for k in snaps["xla"]}
    return {"xla": snaps["xla"], "pallas": snaps["pallas"], "diff": diff}


def main(argv=None) -> dict:
    a = parse_args(argv)
    jax = setup_platform(a.kNodeCount)
    import jax.numpy as jnp

    from sherman_tpu import native
    from sherman_tpu.models import batched
    from sherman_tpu.ops import bits
    from sherman_tpu.utils import Timer, notify_info
    from sherman_tpu.workload.zipf import ZipfGen, uniform_ranks

    B = a.kThreadCount * KCORO * a.ops_per_coro
    n_nodes = a.kNodeCount
    total_batch = B * n_nodes
    if a.exchange == "pallas":
        import json as _json
        if n_nodes < 2 or len(jax.devices()) < 2:
            out = {"metric": "exchange_pallas",
                   "skipped": f"needs a multi-device mesh (nodes="
                              f"{n_nodes}, devices={len(jax.devices())})"}
            print(_json.dumps(out))
            return out
        d = exchange_counter_diff(n_nodes)
        bad = {k: v for k, v in d["diff"].items() if v}
        notify_info("[bench] exchange=pallas drill ok; counter diff vs "
                    "xla: %s", bad or "none (exact match)")
        assert not bad, f"pallas/xla DSM counter divergence: {bad}"
    cluster, tree, eng = build_cluster(
        n_nodes, pages_for_keys(a.keys) // n_nodes or 4096, B,
        exchange_impl=a.exchange)
    notify_info("[bench] nodes=%d read%%=%d threads=%d B/node=%d keys=%d "
                "theta=%.2f", n_nodes, a.kReadRatio, a.kThreadCount, B,
                a.keys, a.theta)

    # --- warmup: bulk-load the warm fraction (benchmark.cpp:114-120) --------
    rng = np.random.default_rng(7)
    keys = np.unique(rng.integers(1, 1 << 63, int(a.keys * 1.05),
                                  dtype=np.uint64))[:a.keys]
    assert keys.shape[0] == a.keys, "keyspace generation came up short"
    n_warm = int(a.keys * WARM_RATIO)
    warm = np.sort(rng.choice(keys, n_warm, replace=False))
    vals = warm ^ np.uint64(0xDEADBEEF)
    t = Timer()
    t.begin()
    stats = batched.bulk_load(tree, warm, vals)
    router = eng.attach_router()
    cluster.keeper.barrier("warm_finish")
    notify_info("[bench] warm %d keys in %.1fs %s", n_warm, t.end() / 1e9,
                stats)

    # --- pre-generate batches (zipf over the warm set) ----------------------
    n_batches = 32
    if a.theta > 0:
        ranks = ZipfGen(n_warm, a.theta, seed=11).sample(
            n_batches * total_batch)
    else:
        ranks = uniform_ranks(n_warm, n_batches * total_batch, rng)
    bkeys = warm[ranks].reshape(n_batches, total_batch)

    # Per-node read count first, global count from it: the tiled per-node
    # [reads | writes] layout must agree exactly with the global split
    # (B * ratio // 100 summed over nodes != total * ratio // 100 when the
    # per-node count doesn't divide evenly).
    r_node = B * a.kReadRatio // 100
    n_read = r_node * n_nodes
    shard = tree.dsm.shard

    def pack_batch(bk, act_r, act_w, salt):
        """Device-side batch dict from key layout + activity masks."""
        khi, klo = bits.keys_to_pairs(bk)
        nv_hi, nv_lo = bits.keys_to_pairs(bk ^ np.uint64(0xBEEF + salt))
        return dict(
            khi=jax.device_put(khi, shard), klo=jax.device_put(klo, shard),
            start=jax.device_put(router.host_start(khi, klo), shard),
            vhi=jax.device_put(nv_hi, shard),
            vlo=jax.device_put(nv_lo, shard),
            act_r=(act_r if hasattr(act_r, "devices")
                   else jax.device_put(act_r, shard)),
            act_w=(act_w if hasattr(act_w, "devices")
                   else jax.device_put(act_w, shard)))

    # Request combining (see bench.py): duplicate lookups in a batch share
    # one descent, and duplicate upserts collapse to their first-ordered
    # writer — exactly the step's own same-key dedup (the winner applies,
    # later duplicates are ST_SUPERSEDED), applied at prep.  Reads and
    # writes dedup separately; a key in both classes keeps per-request
    # semantics (the read sees the pre-step snapshot, the write applies
    # at the boundary — the step's serial order).  EVERY client request's
    # answer (value or status) is fanned out ON DEVICE inside the timed
    # step — pure-read via the engine's fused fan-out kernel, mixed via a
    # packed take_along_axis after the step — so combined client-ops
    # throughput is fully earned in-step (round-2's deferred-fan-out
    # accounting gap, closed).  Write combining is single-node only (the
    # mixed [reads | writes] layout is per-node static); pure-read
    # combining works on any mesh.
    can_combine = n_nodes == 1 or a.kReadRatio == 100
    if a.combine == "on" and not can_combine:
        notify_info("[bench] --combine on ignored: multi-node write "
                    "combining needs per-node static layouts")
    combine = can_combine and a.combine != "off" and (
        a.combine == "on" or a.theta > 0)

    def _cap(lens, limit):
        """Static class capacity: next quantum above the max unique count,
        never above the class's own request count (tiny forced-combine
        runs must not inflate the device batch).  The quantum keeps the
        device batch sharding evenly over the node mesh."""
        quantum = 8192 * n_nodes
        m = max(lens, default=0)
        return min(-(-m // quantum) * quantum, limit) if m else 0

    batches = []
    if combine:
        # per batch: unique reads, unique writes (+ inverse maps for the
        # in-step per-request answer fan-out)
        ur = [np.unique(bkeys[i][:n_read], return_inverse=True)
              for i in range(n_batches)]
        uw = [np.unique(bkeys[i][n_read:], return_inverse=True)
              for i in range(n_batches)]
        r_cap = _cap([u.shape[0] for u, _ in ur], n_read)
        w_cap = _cap([u.shape[0] for u, _ in uw], total_batch - n_read)
        if a.combine == "auto" and (r_cap + w_cap) * 2 > total_batch:
            combine = False  # not enough duplication to pay
        else:
            dev_batch = r_cap + w_cap
            write_lo = r_cap
            notify_info("[bench] combine: %d ops -> dev %d "
                        "(reads %d cap %d, writes %d cap %d); "
                        "per-request fan-out on device in-step",
                        total_batch, dev_batch,
                        max((u.shape[0] for u, _ in ur), default=0), r_cap,
                        max((u.shape[0] for u, _ in uw), default=0), w_cap)
            for i in range(n_batches):
                bk = np.zeros(dev_batch, np.uint64)
                act_r = np.zeros(dev_batch, bool)
                act_w = np.zeros(dev_batch, bool)
                (ukr, invr), (ukw, invw) = ur[i], uw[i]
                nr, nw = ukr.shape[0], ukw.shape[0]
                bk[:nr] = ukr
                act_r[:nr] = True
                bk[r_cap:r_cap + nw] = ukw
                act_w[r_cap:r_cap + nw] = True
                b = pack_batch(bk, act_r, act_w, i)
                # client slot j's answer row in the unique table: reads
                # first (their inverse), then writes offset by r_cap
                inv = np.concatenate([
                    invr.astype(np.int32),
                    (r_cap + invw).astype(np.int32)])
                b["inv"] = jax.device_put(inv, shard)
                batches.append(b)
            del ur, uw
    if not combine:
        # Per-NODE [reads | writes] layout: the mesh shards dim 0
        # contiguously, so each node's chunk holds its reads first — the
        # mixed step then applies writes on a static half-width slice
        # (mixed_step_spmd write_lo), halving the apply cost of a 50/50
        # mix.  Key slots are arbitrary zipf draws, so reassigning which
        # slots are reads is workload-neutral.
        dev_batch = total_batch
        write_lo = r_node
        node_mask = np.zeros(B, bool)
        node_mask[:r_node] = True
        active_r = np.tile(node_mask, n_nodes)
        active_w = ~active_r
        ar_dev = jax.device_put(active_r, shard)
        aw_dev = jax.device_put(active_w, shard)
        for i in range(n_batches):
            # slot-to-class assignment is positional: lay the batch's keys
            # out so each node chunk is [reads | writes]
            bk = np.empty(total_batch, np.uint64)
            bk[active_r] = bkeys[i][:n_read]
            bk[active_w] = bkeys[i][n_read:]
            batches.append(pack_batch(bk, ar_dev, aw_dev, i))
    root = np.int32(tree._root_addr)

    dsm = tree.dsm
    hist = native.LatencyHistogram() if native.available() else None
    mixed = 0 < n_read < total_batch
    # pure-read combined uses the engine's FUSED fan-out kernel (descent
    # over uniques + per-request answer fan-out in ONE program, any mesh
    # size); combined mixed/write-only steps append a packed
    # take_along_axis fan-out program inside the same timed step
    ffn = (eng._get_search_fanout(eng._iters())
           if combine and not mixed and n_read else None)
    mfn = (eng._get_mixed(eng._iters(), True, write_lo=write_lo,
                          update_only=True)
           if mixed else None)
    sfn = (eng._get_search(eng._iters(), True)
           if not mixed and n_read and ffn is None else None)
    # steady-state updates never split nor insert fresh keys: the
    # update-only kernel (4-word write-back, no insert-rank/split
    # machinery; absent keys would report ST_FULL and fail the final
    # verification — the workload draws from the warm set only)
    wfn = (eng._get_insert(eng._iters(), True, with_fresh=False,
                           update_only=True)
           if not mixed and n_read < total_batch else None)

    @jax.jit
    def fan(found, vh, vl, status, inv):
        # per-request fan-out for combined mixed/write-only steps: ONE
        # packed [dev_batch, 4] table, one take — every client slot's
        # (found, value, status) lands in HBM inside the timed step
        ans = jnp.stack([found.astype(jnp.int32), vh, vl, status], axis=-1)
        out = jnp.take_along_axis(ans, inv[:, None], axis=0)
        return out[:, 0].astype(bool), out[:, 1], out[:, 2], out[:, 3]

    zero_dev = (jax.device_put(np.zeros(dev_batch, np.int32), shard)
                if combine and wfn is not None else None)

    def one_step(i):
        b = batches[i % n_batches]
        if ffn is not None:
            # combined pure-read: fused descent + in-step fan-out; the
            # returned found/values are CLIENT-width
            dsm.counters, done, found, vh, vl = ffn(
                dsm.pool, dsm.counters, b["khi"], b["klo"], root,
                b["act_r"], b["start"], b["inv"])
            return found
        if mfn is not None:
            # fused step: searches and upserts share one descent
            (dsm.pool, dsm.counters, dsm.dirty, status, done_r, found,
             vh, vl) = mfn(
                dsm.pool, dsm.locks, dsm.counters, dsm.dirty, b["khi"],
                b["klo"], b["vhi"], b["vlo"], root, b["act_r"],
                b["act_w"], b["start"])
            if combine:
                _, _, _, cst = fan(found, vh, vl, status, b["inv"])
                return cst
            return status
        if sfn is not None:
            dsm.counters, done, found, vh, vl = sfn(
                dsm.pool, dsm.counters, b["khi"], b["klo"], root,
                b["act_r"], b["start"])
            return found
        # steady-state writes update warm keys in place (no splits); a
        # split-heavy load would drive inserts through eng.insert instead
        dsm.pool, dsm.counters, dsm.dirty, status = wfn(
            dsm.pool, dsm.locks, dsm.counters, dsm.dirty, b["khi"],
            b["klo"], b["vhi"], b["vlo"], root, b["act_w"], b["start"])
        if combine:
            _, _, _, cst = fan(zero_dev, zero_dev, zero_dev, status,
                               b["inv"])
            return cst
        return status

    # Multi-node meshes must drain every step: two queued SPMD programs can
    # interleave across device threads (device 1 enters program i+1's
    # all_to_all while device 0 is still in program i's), deadlocking the
    # collective rendezvous.  Single-node programs have no collectives, so
    # deep queueing is safe and hides the access-tunnel sync cost (~100 ms).
    def drain(x):
        np.asarray(jnp.ravel(x)[0])

    # warm + compile + settle
    out = one_step(0)
    drain(out)
    for i in range(8):
        out = one_step(i)
        if n_nodes > 1:
            drain(out)
    drain(out)

    # --- timed windows ------------------------------------------------------
    t0 = time.time()
    for i in range(4):
        out = one_step(i)
        if n_nodes > 1:
            drain(out)
    drain(out)
    est = max((time.time() - t0) / 4, 1e-4)
    # Amortize the drain (~100 ms through the access tunnel) over many
    # steps, but never let one block overrun the report window: target
    # block span = max(0.5 s, 32 steps) capped at the window.
    if n_nodes > 1:
        steps_per_block = 1
    else:
        span = min(max(0.5, 32 * est), a.window)
        steps_per_block = max(1, int(span / est))

    windows = max(1, int(a.secs / a.window))
    notify_info("[bench] est step %.1f ms -> %d steps/block",
                est * 1e3, steps_per_block)
    guard = None
    if a.preempt_ckpt:
        from sherman_tpu.utils import failure
        guard = failure.PreemptionGuard(cluster.keeper)
    preempted = False
    results = []
    step_i = 0
    c_prev = dsm.counter_snapshot()
    for w in range(windows):
        w0 = time.time()
        blocks = 0
        while time.time() - w0 < a.window:
            b0 = time.time()
            for _ in range(steps_per_block):
                out = one_step(step_i)
                step_i += 1
                if n_nodes > 1:
                    drain(out)
            drain(out)
            span = time.time() - b0
            blocks += 1
            if hist is not None:
                hist.record_batch(int(span / steps_per_block * 1e9),
                                  total_batch * steps_per_block)
            # block boundary = the agreed stopping granularity: in
            # multihost every process polls with the same step_i
            # (replicated control flow) and the sync manager flips them
            # all at the SAME boundary
            if guard is not None and guard.should_act(step_i):
                preempted = True
                break
        if preempted:
            # the eviction clock is ticking (SIGTERM-to-SIGKILL notice is
            # ~seconds): checkpoint FIRST, skip scans and reporting
            from sherman_tpu.utils import checkpoint as CK
            CK.checkpoint(cluster, a.preempt_ckpt)
            print(f"[bench] preemption notice: checkpointed to "
                  f"{a.preempt_ckpt} at step {step_i}; stopping",
                  flush=True)
            break
        elapsed = time.time() - w0
        # range scans (config 5: mixed + range-scan — sibling-link
        # traversal over the cache-seeded prefetch, Tree.cpp:461-522).
        # Timed separately AFTER the window closes so the point-op
        # throughput (ops/elapsed) is not deflated by scan time.
        scan_entries = scan_ns = 0
        if a.scans:
            # BATCHED scans: candidate leaves of every range prefetched
            # in ONE device gather (range_query_many — the multi-scan
            # form of the reference's kParaFetch window)
            rq = []
            for s in range(a.scans):
                i0 = int(rng.integers(0, max(1, n_warm - a.scan_span)))
                lo = int(warm[i0])
                hi = int(warm[min(n_warm - 1, i0 + a.scan_span)])
                rq.append((lo, max(hi, lo + 1)))
            s0 = time.time_ns()
            res = eng.range_query_many(rq)
            scan_ns = time.time_ns() - s0
            scan_entries = sum(k.size for k, _ in res)
        ops = blocks * steps_per_block * total_batch
        tp_node = ops / elapsed / n_nodes
        tp_cluster = cluster.keeper.sum(f"tp:{w}", int(ops / elapsed))
        c_now = dsm.counter_snapshot()
        reads = c_now["read_ops"] - c_prev["read_ops"]
        c_prev = c_now
        line = (f"[window {w}] node tp {tp_node / 1e6:.2f} Mops/s, "
                f"cluster tp {tp_cluster / 1e6:.2f} Mops/s, "
                f"reads/op {reads / max(ops, 1):.2f}")
        if combine:
            # both metrics so combined client-ops and raw device-row
            # throughput can't be conflated: client tp counts each
            # duplicate request AND its answer is materialized on device
            # inside the timed step (the in-step fan-out above), so the
            # client number is fully earned; dev rows is the conservative
            # unique-row denominator
            dev_tp = blocks * steps_per_block * dev_batch / elapsed
            line += (f", dev rows {dev_tp / 1e6:.2f} M/s "
                     f"(combine {total_batch / dev_batch:.1f}x, "
                     "in-step fan-out)")
        if a.scans:
            line += (f", scans {a.scans} x {scan_entries // max(a.scans, 1)} "
                     f"entries @ {scan_ns / max(a.scans, 1) / 1e6:.1f} ms "
                     f"amortized ({scan_entries / max(scan_ns, 1) * 1e9 / 1e6:.2f} M entries/s)")
        if hist is not None and w % 3 == 2:
            line += f", lat(us) {hist.percentiles_us()}"
        print(line, flush=True)
        results.append(tp_cluster)

    # --- verify the last step's statuses (writes must have applied) --------
    last_b = batches[(step_i - 1) % n_batches]
    if mfn is not None or wfn is not None:
        st = np.asarray(out)
        if combine:
            # client-width fanned statuses: write slots are [n_read:]
            okw = np.isin(st[n_read:],
                          (batched.ST_APPLIED, batched.ST_SUPERSEDED))
        else:
            okw = np.isin(st[np.asarray(last_b["act_w"])],
                          (batched.ST_APPLIED, batched.ST_SUPERSEDED))
        assert okw.mean() > 0.99, f"write fast-path misses: {1-okw.mean():.3%}"
    elif ffn is not None:
        # client-width fanned lookups: every request key is warm
        assert bool(np.asarray(out).all()), "combined searches missed keys"
    elif sfn is not None:
        found = np.asarray(out)[np.asarray(last_b["act_r"])]
        assert bool(found.all()), "searches missed warm keys"

    best = max(results, default=0)  # empty when preempted in window 0
    print(f"[bench] peak cluster throughput {best / 1e6:.2f} Mops/s "
          f"({a.kReadRatio}% read, theta={a.theta})")
    return {"peak_ops": best, "windows": results, "preempted": preempted}


if __name__ == "__main__":
    main()
