#!/usr/bin/env python
"""YCSB-style benchmark driver — ``test/benchmark.cpp`` parity.

CLI contract (benchmark.cpp:193-205):

    python tools/benchmark.py <kNodeCount> <kReadRatio> <kThreadCount>
        [--keys N] [--theta T] [--secs S] [--ops-per-coro N] [--windows W]

- ``kNodeCount``   — cluster nodes (mesh size; 1 = the real chip, >1 runs
  on a virtual CPU mesh when the hardware doesn't have that many chips).
- ``kReadRatio``   — percent of operations that are searches (YCSB-C=100,
  YCSB-B=95, YCSB-A=50); the rest are upserts.
- ``kThreadCount`` — client threads per node.  The reference keeps
  kThreadCount x kCoroCnt ops in flight per node (``Tree.cpp:1059-1122``);
  the batched engine realizes the same concurrency as one step of
  B = kThreadCount x kCoroCnt x opsPerCoro keys.

Workload (benchmark.cpp:15-24,159-188): keyspace of --keys unique keys,
warm ratio 0.8 bulk-loaded, zipf(--theta) sampling over the warm set.
Reports per 2-second window: per-node + cluster throughput (via
keeper.sum, DSMKeeper.cpp:163-176), reads/op, and every 3rd window the
p50/p90/p95/p99/p999 op latency from the native 0.1 us histogram
(cal_latency, benchmark.cpp:207-249).  In the batched execution model a
key's completion latency IS its step's latency, so each step records
(span, batch) into the histogram.
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np

from common import build_cluster, pages_for_keys, setup_platform

KCORO = 8          # kCoroCnt (Common.h:62-71)
WARM_RATIO = 0.8   # kWarmRatio (benchmark.cpp:19)


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("kNodeCount", type=int)
    p.add_argument("kReadRatio", type=int)
    p.add_argument("kThreadCount", type=int)
    p.add_argument("--keys", type=int, default=1_000_000)
    p.add_argument("--theta", type=float, default=0.99)
    p.add_argument("--secs", type=float, default=10.0)
    p.add_argument("--ops-per-coro", type=int, default=64,
                   help="batched ops per (thread, coroutine) slot")
    p.add_argument("--window", type=float, default=2.0,
                   help="report window seconds (benchmark.cpp:300)")
    p.add_argument("--combine", choices=("auto", "on", "off"),
                   default="auto",
                   help="read-request combining: duplicate lookups in a "
                        "batch share one descent (auto: on for read-only "
                        "skewed workloads)")
    p.add_argument("--scans", type=int, default=0,
                   help="range scans per report window (the multi-node "
                        "mixed + range-scan config: exercises sibling-link "
                        "traversal, Tree.cpp:461-522)")
    p.add_argument("--scan-span", type=int, default=1000,
                   help="target entries per range scan")
    return p.parse_args(argv)


def main(argv=None) -> dict:
    a = parse_args(argv)
    jax = setup_platform(a.kNodeCount)
    import jax.numpy as jnp

    from sherman_tpu import native
    from sherman_tpu.models import batched
    from sherman_tpu.ops import bits
    from sherman_tpu.utils import Timer, notify_info
    from sherman_tpu.workload.zipf import ZipfGen, uniform_ranks

    B = a.kThreadCount * KCORO * a.ops_per_coro
    n_nodes = a.kNodeCount
    total_batch = B * n_nodes
    cluster, tree, eng = build_cluster(
        n_nodes, pages_for_keys(a.keys) // n_nodes or 4096, B)
    notify_info("[bench] nodes=%d read%%=%d threads=%d B/node=%d keys=%d "
                "theta=%.2f", n_nodes, a.kReadRatio, a.kThreadCount, B,
                a.keys, a.theta)

    # --- warmup: bulk-load the warm fraction (benchmark.cpp:114-120) --------
    rng = np.random.default_rng(7)
    keys = np.unique(rng.integers(1, 1 << 63, int(a.keys * 1.05),
                                  dtype=np.uint64))[:a.keys]
    assert keys.shape[0] == a.keys, "keyspace generation came up short"
    n_warm = int(a.keys * WARM_RATIO)
    warm = np.sort(rng.choice(keys, n_warm, replace=False))
    vals = warm ^ np.uint64(0xDEADBEEF)
    t = Timer()
    t.begin()
    stats = batched.bulk_load(tree, warm, vals)
    router = eng.attach_router()
    cluster.keeper.barrier("warm_finish")
    notify_info("[bench] warm %d keys in %.1fs %s", n_warm, t.end() / 1e9,
                stats)

    # --- pre-generate batches (zipf over the warm set) ----------------------
    n_batches = 32
    if a.theta > 0:
        ranks = ZipfGen(n_warm, a.theta, seed=11).sample(
            n_batches * total_batch)
    else:
        ranks = uniform_ranks(n_warm, n_batches * total_batch, rng)
    bkeys = warm[ranks].reshape(n_batches, total_batch)

    n_read = total_batch * a.kReadRatio // 100
    shard = tree.dsm.shard

    # Read-request combining (see bench.py): duplicate lookups in a batch
    # share one descent.  Only the pure-read workload combines — a mixed
    # batch's read/write interleaving semantics stay per-request.
    if a.combine == "on" and a.kReadRatio != 100:
        notify_info("[bench] --combine on ignored: only kReadRatio=100 "
                    "workloads combine")
    combine = a.kReadRatio == 100 and (
        a.combine == "on" or (a.combine == "auto" and a.theta > 0))
    dev_batch = total_batch
    if combine:
        uniq = [np.unique(bkeys[i]) for i in range(n_batches)]
        max_u = max(u.shape[0] for u in uniq)
        if a.combine == "auto" and max_u * 2 > total_batch:
            combine = False  # not enough duplication to pay
        else:
            # device batch must shard evenly over the node mesh
            quantum = 8192 * n_nodes
            dev_batch = min(-(-max_u // quantum) * quantum, total_batch)
            notify_info("[bench] combine: %d ops -> %d unique (dev %d)",
                        total_batch, max_u, dev_batch)

    batches = []
    for i in range(n_batches):
        bk = bkeys[i]
        act_n = dev_batch
        if combine:
            uk = uniq[i]
            act_n = uk.shape[0]
            bk = np.pad(uk, (0, dev_batch - act_n))
        khi, klo = bits.keys_to_pairs(bk)
        start = router.host_start(khi)
        nv_hi, nv_lo = bits.keys_to_pairs(bk ^ np.uint64(0xBEEF + i))
        act = np.zeros(dev_batch, bool)
        act[:act_n] = True
        batches.append(dict(
            khi=jax.device_put(khi, shard), klo=jax.device_put(klo, shard),
            start=jax.device_put(start, shard),
            vhi=jax.device_put(nv_hi, shard),
            vlo=jax.device_put(nv_lo, shard),
            act=jax.device_put(act, shard)))
    if combine:
        del uniq
    n_read_dev = dev_batch * a.kReadRatio // 100
    active_r = np.zeros(dev_batch, bool)
    active_r[:n_read_dev] = True
    active_w = ~active_r
    if combine:
        active_r = None  # combined mode is read-only; per-batch act masks
        active_w = None
    else:
        active_r = jax.device_put(active_r, shard)
        active_w = jax.device_put(active_w, shard)
    root = np.int32(tree._root_addr)

    dsm = tree.dsm
    hist = native.LatencyHistogram() if native.available() else None
    mixed = 0 < n_read < total_batch
    mfn = eng._get_mixed(eng._iters(), True) if mixed else None
    sfn = (eng._get_search(eng._iters(), True)
           if not mixed and n_read else None)
    wfn = (eng._get_insert(eng._iters(), True)
           if not mixed and n_read < total_batch else None)

    def one_step(i):
        b = batches[i % n_batches]
        if mfn is not None:
            # fused step: searches and upserts share one descent
            (dsm.pool, dsm.counters, status, done_r, found, vh, vl) = mfn(
                dsm.pool, dsm.locks, dsm.counters, b["khi"], b["klo"],
                b["vhi"], b["vlo"], root, active_r, active_w, b["start"])
            return status
        if sfn is not None:
            act = b["act"] if combine else active_r
            dsm.counters, done, found, vh, vl = sfn(
                dsm.pool, dsm.counters, b["khi"], b["klo"], root, act,
                b["start"])
            return found
        dsm.pool, dsm.counters, status = wfn(
            dsm.pool, dsm.locks, dsm.counters, b["khi"], b["klo"],
            b["vhi"], b["vlo"], root, active_w, b["start"])
        return status

    # Multi-node meshes must drain every step: two queued SPMD programs can
    # interleave across device threads (device 1 enters program i+1's
    # all_to_all while device 0 is still in program i's), deadlocking the
    # collective rendezvous.  Single-node programs have no collectives, so
    # deep queueing is safe and hides the access-tunnel sync cost (~100 ms).
    def drain(x):
        np.asarray(jnp.ravel(x)[0])

    # warm + compile + settle
    out = one_step(0)
    drain(out)
    for i in range(8):
        out = one_step(i)
        if n_nodes > 1:
            drain(out)
    drain(out)

    # --- timed windows ------------------------------------------------------
    t0 = time.time()
    for i in range(4):
        out = one_step(i)
        if n_nodes > 1:
            drain(out)
    drain(out)
    est = max((time.time() - t0) / 4, 1e-4)
    # Amortize the drain (~100 ms through the access tunnel) over many
    # steps, but never let one block overrun the report window: target
    # block span = max(0.5 s, 32 steps) capped at the window.
    if n_nodes > 1:
        steps_per_block = 1
    else:
        span = min(max(0.5, 32 * est), a.window)
        steps_per_block = max(1, int(span / est))

    windows = max(1, int(a.secs / a.window))
    notify_info("[bench] est step %.1f ms -> %d steps/block",
                est * 1e3, steps_per_block)
    results = []
    step_i = 0
    c_prev = dsm.counter_snapshot()
    for w in range(windows):
        w0 = time.time()
        blocks = 0
        while time.time() - w0 < a.window:
            b0 = time.time()
            for _ in range(steps_per_block):
                out = one_step(step_i)
                step_i += 1
                if n_nodes > 1:
                    drain(out)
            drain(out)
            span = time.time() - b0
            blocks += 1
            if hist is not None:
                hist.record_batch(int(span / steps_per_block * 1e9),
                                  total_batch * steps_per_block)
        elapsed = time.time() - w0
        # range scans (config 5: mixed + range-scan — sibling-link
        # traversal over the cache-seeded prefetch, Tree.cpp:461-522).
        # Timed separately AFTER the window closes so the point-op
        # throughput (ops/elapsed) is not deflated by scan time.
        scan_entries = scan_ns = 0
        for s in range(a.scans):
            span_keys = a.scan_span
            i0 = int(rng.integers(0, max(1, n_warm - span_keys)))
            lo = int(warm[i0])
            hi = int(warm[min(n_warm - 1, i0 + span_keys)])
            s0 = time.time_ns()
            ks, _ = eng.range_query(lo, max(hi, lo + 1))
            scan_ns += time.time_ns() - s0
            scan_entries += ks.size
        ops = blocks * steps_per_block * total_batch
        tp_node = ops / elapsed / n_nodes
        tp_cluster = cluster.keeper.sum(f"tp:{w}", int(ops / elapsed))
        c_now = dsm.counter_snapshot()
        reads = c_now["read_ops"] - c_prev["read_ops"]
        c_prev = c_now
        line = (f"[window {w}] node tp {tp_node / 1e6:.2f} Mops/s, "
                f"cluster tp {tp_cluster / 1e6:.2f} Mops/s, "
                f"reads/op {reads / max(ops, 1):.2f}")
        if a.scans:
            line += (f", scans {a.scans} x {scan_entries // max(a.scans, 1)} "
                     f"entries @ {scan_ns / max(a.scans, 1) / 1e6:.1f} ms")
        if hist is not None and w % 3 == 2:
            line += f", lat(us) {hist.percentiles_us()}"
        print(line, flush=True)
        results.append(tp_cluster)

    # --- verify the last step's statuses (writes must have applied) --------
    if mfn is not None or wfn is not None:
        st = np.asarray(out)
        okw = np.isin(st[np.asarray(active_w)],
                      (batched.ST_APPLIED, batched.ST_SUPERSEDED))
        assert okw.mean() > 0.99, f"write fast-path misses: {1-okw.mean():.3%}"
    elif sfn is not None:
        found = np.asarray(out)
        if combine:
            found = found[np.asarray(
                batches[(step_i - 1) % n_batches]["act"])]
        assert bool(found.all()), "searches missed warm keys"

    best = max(results)
    print(f"[bench] peak cluster throughput {best / 1e6:.2f} Mops/s "
          f"({a.kReadRatio}% read, theta={a.theta})")
    return {"peak_ops": best, "windows": results}


if __name__ == "__main__":
    main()
