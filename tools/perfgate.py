#!/usr/bin/env python
"""Perf-regression gate over the committed BENCH_r*.json trajectory.

The published throughput trajectory (BENCH_r01..r05 at the repo root)
is the contract every perf PR must not silently regress.  This tool
parses the committed rounds plus a fresh receipt, applies NOISE-AWARE
thresholds, and exits nonzero on regression — the CI lane
(``scripts/obs_ci.sh``) runs it against the committed r05 receipt so
the gate itself is pinned green on known-good data, and against a
synthetically degraded receipt so it is pinned RED on a real loss.

Noise calibration: the round-5 capture measured 33.8 M ops/s in the
log and 32.2 M in the JSON for the SAME configuration minutes apart
(BENCHMARKS.md row-1 annotation) — a ~5% same-build run spread through
the access tunnel.  The default margin is ``max(--min-margin,
--spread-mult x max(calibrated spread, observed cross-round spread))``
per metric: with the defaults (min 10%, mult 2.0) a -20% sustained
loss FAILS while the r05-vs-r05 and r02-r05 cross-round wiggles (~1-7%)
PASS.

Comparability rules (the trajectory's own lessons):

- only rounds with the same ``keys`` and ``batch`` as the candidate
  compare (r01's retracted 107 M predates the accounting and carries
  no config — it filters itself out);
- a NODE-COUNT change is incomparable config: an elastic reshard
  (``bench.py --reshard-drill``, ``sherman_tpu/migrate.py``) changes
  the per-node workload and the exchange topology wholesale, so a
  receipt captured at M nodes never gates against a round captured at
  N != M — reshard-drill receipts themselves carry their own metric
  (``reshard_drill``) and are not bench receipts at all (feeding one
  here exits 2: no comparable metric).  Rounds predating the ``nodes``
  field compare as 1-node runs — ``bench.py`` hardcoded
  ``machine_nr=1`` for the whole committed trajectory, so the default
  is a fact, not a guess;
- ``sustained_ops_s`` compares only between device-staged runs (both
  sides must carry ``sus_dev_ms_per_step``): r04's host-shipped 3.9 M
  is a different methodology and must never become the baseline;
- the hot-key leaf cache (the optional schema-3 ``cache`` block) is
  comparable-config metadata: a cache-ON receipt's ``sustained_ops_s``
  never gates against a cache-OFF round's and vice versa — most ops of
  a cache-ON loop never descend, a different workload per step;
- a VALUE-CONFIG change is incomparable config (PR 14): rows whose
  ``config.value_bytes`` / ``config.value_dist`` / ``config.value_heap``
  differ never gate against each other — out-of-line heap reads gather
  payload pages inline reads never touch, and payload size rescales
  every byte-bound phase.  Receipts predating the fields compare as
  fixed-width 8-byte inline (the hardcoded pre-heap fact), so the
  committed trajectory keeps gating;
- SERVE-MODE receipts (``tools/serve_bench.py`` / ``bench.py --serve``
  — the open-loop, admission-paced front door; identified by the
  ``serve`` block or ``metric == "serve_bench"``) are a different
  methodology wholesale: a front-door receipt NEVER gates against a
  closed-loop round's ``sustained_ops_s`` (or any other closed-loop
  metric) and vice versa — an open loop pays admission pacing,
  queueing and per-request acks the closed loop does not, so the
  comparison would manufacture regressions both ways.  WITHIN
  serve-mode rounds, per-class p99 (``serve_read_p99_ms`` /
  ``serve_write_p99_ms``, lower-is-better) and open-loop throughput
  (``serve_ops_s``) gate with the same noise-margin rule — but only
  between rounds whose ``serve.p99_targets_ms`` match: a target change
  re-aims the adaptive controller, which is a config change, not a
  regression;
- CLIENT-CONTRACT receipts (``tools/contract_drill.py``, metric
  ``contract_drill``) are robustness artifacts, never throughput-gated
  — but their pins are HARD reds with no margin (the retrace-red
  pattern): ``duplicate_acks > 0``, ``lost_acks > 0`` or
  ``linearizable == false`` in a committed receipt fails the gate
  outright; with the pins green the receipt passes on them alone
  (no comparable throughput metric required);
- REPLICATION (PR 16) is incomparable config: a receipt with the
  replication plane ON (a ``repl`` block, a ``replicas`` config, or
  metric ``failover_drill``) never throughput-gates against
  unreplicated rounds — the follower tier re-applies every journaled
  write R more times in the same process.  Failover-drill receipts
  carry the same marginless hard-red pins as contract receipts
  (``lost_acks`` / ``duplicate_acks`` / ``linearizable``);
- QUORUM ACKS (PR 18) are incomparable config: a receipt whose
  effective ``ack_quorum`` differs (the ``repl.quorum.ack_quorum`` /
  ``config.ack_quorum`` field; missing = 1, the shipped primary-only
  default) never throughput-gates in EITHER direction — a
  quorum-gated ack waits on follower durability the primary-only ack
  never pays, and comparing the other way would launder the wait as a
  win.  Partition-drill receipts (``tools/partition_drill.py``,
  metric ``partition_drill``) carry the contract hard-red pins plus
  two of their own, ``fenced_acks_merged > 0`` and
  ``diverged_followers_unrepaired > 0`` — each a zero-tolerance
  split-brain/divergence verdict, marginless;
- a HOST-COUNT change is incomparable config (PR 19): rows whose
  ``config.hosts`` differ (missing = 1, the pre-multihost fact) never
  throughput-gate in either direction — N per-host journal streams
  fsync in parallel and N front doors admit independently, so a
  multihost number is a different service plane, not a faster one.
  Multihost-drill receipts (``tools/multihost_drill.py``, metric
  ``multihost_drill``) carry the contract hard-red pins plus
  ``rpo_ops > 0`` — an acked op missing after union recovery is lost
  durability, marginless; the drill's ack-bandwidth speedup is
  published in the receipt, never gated here against hosts=1 rounds;
- a PREP-PLACEMENT change is incomparable config (PR 17): rows whose
  ``config.prep_impl`` or ``config.write_combine`` differ never
  throughput-gate against each other — host prep serializes
  ``np.unique``/sort/route wall clock into every step that device prep
  moves onto the chip, and write combining changes the lock-acquisition
  count per batch wholesale.  Receipts predating the fields compare as
  ``("host", False)`` (the hardcoded pre-PR-17 fact), so the committed
  trajectory keeps gating;
- a metric missing on either side is skipped, not failed — but a
  candidate with NO comparable metric at all exits 2 (the gate cannot
  vouch for it).

White-box device gates (schema_version 3, the ``device`` section): a
candidate carrying the compile ledger goes RED on ``retraces > 0`` —
bench.py seals the ledger around every timed window, so any counted
retrace is a real steady-state recompile, a hard fail with no margin —
and on an ``achieved_bytes_frac`` drop beyond the noise-margin rule on
any roofline phase both sides publish.  Rounds without a ``device``
section (schema 1/2, r01-r07) simply skip the device gates — older
artifacts stay comparable on the throughput metrics, never crash the
gate.

Usage::

    python tools/perfgate.py --receipt BENCH_r05.json        # pass pin
    python tools/perfgate.py --receipt fresh.json            # gate a run
    python tools/perfgate.py --receipt f.json --json         # receipt only

Receipts may be the driver-wrapped form (``{"n": .., "parsed": {...}}``
— the committed BENCH_r*.json shape) or a bare bench JSON line.  Exit
codes: 0 pass, 1 regression, 2 unusable input.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

# round-5 same-config run spread: 33.8 M (log) vs 32.2 M (JSON) — the
# measured single-build noise floor this gate's thresholds anchor on
CALIBRATED_SPREAD = 33.8 / 32.2 - 1.0  # ~0.050

# watched metrics: (name, higher_is_better)
METRICS = (
    ("value", True),             # headline client ops/s
    ("sustained_ops_s", True),   # device-staged open loop (r05+)
    ("sus_mixed_ops_s", True),   # YCSB-A mixed loop
    ("p99_ms", False),           # step-span tail latency
    # serve-mode metrics (r12+, gate only within serve-mode rounds at
    # matching p99 targets — see the comparability rules)
    ("serve_ops_s", True),       # open-loop front-door throughput
    ("serve_read_p99_ms", False),   # end-to-end per-request read p99
    ("serve_write_p99_ms", False),  # end-to-end per-request write p99
)


def load_receipt(path: str) -> dict:
    """One receipt: driver-wrapped ({"parsed": {...}}) or bare."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        parsed = dict(doc["parsed"])
        parsed.setdefault("_round", doc.get("n"))
        return parsed
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a bench receipt")
    return doc


def load_trajectory(repo: str) -> list[dict]:
    """Committed BENCH_r*.json receipts, ascending by round."""
    rounds = []
    for p in sorted(glob.glob(os.path.join(repo, "BENCH_r*.json"))):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        if not m:
            continue
        try:
            r = load_receipt(p)
        except (ValueError, json.JSONDecodeError):
            continue
        r["_round"] = int(m.group(1))
        r["_path"] = p
        rounds.append(r)
    return sorted(rounds, key=lambda r: r["_round"])


def _device_fracs(r: dict) -> dict:
    """``{group.phase: achieved_bytes_frac}`` from a schema-3 receipt's
    ``device.rooflines`` block; {} when the section (or the fraction —
    unknown-peak devices publish absolute rates only) is absent."""
    dev = r.get("device")
    if not isinstance(dev, dict):
        return {}
    out = {}
    for group, phases in (dev.get("rooflines") or {}).items():
        if not isinstance(phases, dict):
            continue
        for phase, rec in phases.items():
            f = (rec.get("achieved_bytes_frac")
                 if isinstance(rec, dict) else None)
            if isinstance(f, (int, float)) and f > 0:
                out[f"{group}.{phase}"] = float(f)
    return out


def _cache_on(r: dict) -> bool:
    """True when the receipt's device-staged loop ran with the hot-key
    leaf cache enabled (the optional schema-3 ``cache`` block; absent
    block = cache off — every pre-cache round)."""
    c = r.get("cache")
    return bool(isinstance(c, dict) and c.get("enabled"))


def _value_cfg(r: dict) -> tuple:
    """The receipt's value configuration (config.value_bytes /
    value_dist / value_heap, PR 14).  Absent fields = the pre-heap
    fact: every committed round ran fixed-width 8-byte inline values
    (bench.py hardcoded them until the fields existed), so older
    artifacts compare as (8, "fixed", False) rather than skipping."""
    c = r.get("config") or {}
    return (c.get("value_bytes") or 8,
            c.get("value_dist") or "fixed",
            bool(c.get("value_heap")))


def _prep_cfg(r: dict) -> tuple:
    """The receipt's request-plane placement (config.prep_impl /
    write_combine, PR 17).  Absent fields = the pre-PR-17 fact: every
    committed round ran host prep with combining off (both knobs ship
    OFF and the fields didn't exist), so older artifacts compare as
    ("host", False) rather than skipping."""
    c = r.get("config") or {}
    return (c.get("prep_impl") or "host", bool(c.get("write_combine")))


def _serve_mode(r: dict) -> bool:
    """True for a serving-front-door receipt (open-loop, admission-
    paced — ``tools/serve_bench.py``): the ``serve`` block or the
    ``serve_bench`` metric name.  Serve-mode and closed-loop receipts
    never gate against each other (different methodology wholesale —
    see the module docstring's comparability rules)."""
    return bool(isinstance(r.get("serve"), dict)
                or r.get("metric") == "serve_bench")


def _replicated(r: dict) -> bool:
    """A receipt ran with the replication plane ON: a ``repl`` block
    (the ReplicaGroup's receipt), a follower count in its config, or
    the failover-drill metric itself.  Missing everything = the
    unreplicated fact (replication is OFF by default), so the whole
    committed trajectory keeps comparing."""
    if isinstance(r.get("repl"), dict) \
            or r.get("metric") in ("failover_drill",
                                   "partition_drill"):
        return True
    return bool(r.get("replicas")
                or (r.get("config") or {}).get("replicas"))


def _quorum_cfg(r: dict) -> int:
    """The receipt's effective ``ack_quorum`` (PR 18).  Missing
    everywhere = 1, the shipped primary-only default — so the whole
    committed trajectory keeps comparing.  Quorum-gated rounds wait
    on follower durability per ack; they never throughput-gate
    against primary-only rounds in either direction."""
    q = (r.get("repl") or {}).get("quorum")
    if isinstance(q, dict) and q.get("ack_quorum"):
        return int(q["ack_quorum"])
    return int(r.get("ack_quorum")
               or (r.get("config") or {}).get("ack_quorum")
               or (r.get("serve") or {}).get("ack_quorum") or 1)


def _hosts_cfg(r: dict) -> int:
    """The receipt's host count (config.hosts, PR 19).  Absent
    everywhere = 1, the pre-multihost fact: every committed round ran
    one host's front door and one journal stream — so the whole
    committed trajectory keeps comparing.  A multihost round fsyncs N
    journal streams in parallel and admits through N width
    controllers; its numbers never gate against single-host rounds in
    either direction (the PR 12 ``nodes`` rule's pattern)."""
    return int((r.get("config") or {}).get("hosts")
               or r.get("hosts") or 1)


def _comparable(cand: dict, r: dict, metric: str) -> bool:
    if r.get("keys") != cand.get("keys") \
            or r.get("batch") != cand.get("batch"):
        return False
    # serve-mode wall: front-door receipts gate only within serve-mode
    # rounds, closed-loop receipts only within closed-loop rounds
    if _serve_mode(cand) != _serve_mode(r):
        return False
    # replication wall (PR 16): a replicated round's follower tier
    # re-applies every journaled write R more times in the same
    # process — its walls and throughputs never gate against
    # unreplicated rounds (and vice versa)
    if _replicated(cand) != _replicated(r):
        return False
    # quorum-ack wall (PR 18): differing effective ack_quorum never
    # gates in either direction — the K>1 ack pays a follower-
    # durability wait the primary-only ack does not
    if _quorum_cfg(cand) != _quorum_cfg(r):
        return False
    if metric.startswith("serve_"):
        # per-class p99 gates only between rounds aiming at the SAME
        # targets — a re-aimed controller is a config change
        if (cand.get("serve") or {}).get("p99_targets_ms") \
                != (r.get("serve") or {}).get("p99_targets_ms"):
            return False
    # node-count rule (see the docstring): a reshard changes the
    # per-node workload — different node counts never compare.  A
    # receipt without the field ran machine_nr=1 (the pre-field
    # bench.py hardcoded it).
    if (r.get("nodes") or 1) != (cand.get("nodes") or 1):
        return False
    # host-count rule (PR 19): differing host counts never compare —
    # N per-host journal streams ack in parallel and N front doors
    # admit independently, so a multihost number is a different
    # service plane, not a faster one.  Missing field = hosts=1 (the
    # pre-multihost fact), so the committed trajectory keeps
    # comparing.
    if _hosts_cfg(r) != _hosts_cfg(cand):
        return False
    # value-config rule (PR 14): rows with differing value_bytes /
    # value_dist / value_heap never gate against each other — an
    # out-of-line heap read gathers payload pages the inline read never
    # touches, and a payload-size change rescales every byte-bound
    # phase.  Missing fields = the pre-heap inline fact (see
    # _value_cfg), so the whole committed trajectory keeps comparing.
    if _value_cfg(r) != _value_cfg(cand):
        return False
    # prep-placement rule (PR 17): differing config.prep_impl or
    # config.write_combine never gate against each other — host prep
    # pays np.unique/sort/route wall clock device prep doesn't, and
    # combining changes locks-per-batch wholesale.  Missing fields =
    # ("host", False), the pre-field fact (see _prep_cfg), so the
    # committed trajectory keeps comparing.
    if _prep_cfg(r) != _prep_cfg(cand):
        return False
    if r.get(metric) is None or cand.get(metric) is None:
        return False
    if metric == "sustained_ops_s":
        # device-staged methodology on BOTH sides (r04's host-shipped
        # sustained number is not this metric's baseline)
        if not r.get("sus_dev_ms_per_step") \
                or not cand.get("sus_dev_ms_per_step"):
            return False
        # hot-key-cache comparability: the ``cache`` block is
        # comparable-config METADATA, not a gated number — a cache-ON
        # sustained loop serves most ops without descending, so it
        # never gates against a cache-OFF round (and vice versa; the
        # same rule as device-staged-vs-device-staged above)
        if _cache_on(r) != _cache_on(cand):
            return False
    return True


def _margin_entry(val: float, comp: list[tuple], higher: bool, *,
                  spread_mult: float, min_margin: float) -> dict:
    """One metric's noise-margin verdict from its ``(round, value)``
    history: baseline = the latest comparable round, margin =
    max(min_margin, spread_mult * max(calibrated, observed cross-round
    spread)).  Shared by the throughput/wall loop and the device
    bytes-frac gate so the two noise rules can't drift apart."""
    base_round, baseline = comp[-1]
    vals = [v for _, v in comp]
    observed_spread = (max(vals) / min(vals) - 1.0) \
        if min(vals) > 0 and len(vals) > 1 else 0.0
    margin = max(min_margin,
                 spread_mult * max(CALIBRATED_SPREAD, observed_spread))
    ratio = val / baseline if baseline else 1.0
    ok = ratio >= 1.0 - margin if higher else ratio <= 1.0 + margin
    return {
        "candidate": val,
        "baseline": baseline,
        "baseline_round": base_round,
        "ratio": round(ratio, 4),
        "margin": round(margin, 4),
        "observed_spread": round(observed_spread, 4),
        "direction": "higher" if higher else "lower",
        "ok": ok,
    }


def gate(cand: dict, rounds: list[dict], *, spread_mult: float = 2.0,
         min_margin: float = 0.10) -> dict:
    """-> {"ok": bool, "metrics": {name: {...}}, ...}; pure function of
    the receipts so tests can drive it directly."""
    out: dict = {"metric": "perfgate", "ok": True, "metrics": {},
                 "calibrated_spread": round(CALIBRATED_SPREAD, 4),
                 "spread_mult": spread_mult, "min_margin": min_margin}
    # never gate a committed round against itself: a receipt carrying a
    # round number (the driver-wrapped BENCH_rNN form) is compared to
    # the rounds BEFORE it; a bare fresh receipt gates on the full
    # trajectory
    cand_round = cand.get("_round")
    history = [r for r in rounds
               if cand_round is None or r["_round"] < cand_round]
    for name, higher in METRICS:
        comp = [r for r in history if _comparable(cand, r, name)]
        if not comp:
            out["metrics"][name] = {"skipped": "no comparable round"}
            continue
        entry = _margin_entry(
            float(cand[name]),
            [(r["_round"], float(r[name])) for r in comp],
            higher, spread_mult=spread_mult, min_margin=min_margin)
        out["metrics"][name] = entry
        if not entry["ok"]:
            out["ok"] = False
    # the comparability contract is about the THROUGHPUT trajectory:
    # device gates below are self-contained extras and must not rescue
    # a receipt no committed round can vouch for
    gated = [n for n, d in out["metrics"].items() if "ok" in d]
    out["gated_metrics"] = gated
    if not gated:
        out["ok"] = False
        out["error"] = ("no comparable metric between the candidate and "
                        "the committed trajectory (keys/batch mismatch?)")

    # -- white-box device gates (schema_version 3 "device" section) ----------
    dev = cand.get("device")
    if isinstance(dev, dict):
        # steady-state retraces: bench.py seals the compile ledger
        # around every timed window, so ANY counted retrace is a real
        # silent recompile in steady state — a hard red, no noise
        # margin (it is a count of a hazard, not a wall)
        retr = int((dev.get("ledger") or {}).get("retraces", 0) or 0)
        rok = retr == 0
        out["metrics"]["device.retraces"] = {
            "candidate": retr, "baseline": 0, "direction": "zero",
            "ok": rok}
        out["gated_metrics"].append("device.retraces")
        if not rok:
            out["ok"] = False
        # achieved-bytes-fraction per published roofline phase: the
        # serve programs' fraction-of-peak must not silently sink.
        # Compare only against prior rounds that also publish the
        # fraction (schema >= 3 AND a known-peak device) at the same
        # keys/batch; everything older skips.
        hist_fracs = [(r, _device_fracs(r)) for r in history
                      if r.get("keys") == cand.get("keys")
                      and r.get("batch") == cand.get("batch")]
        cand_fracs = _device_fracs(cand)
        for name, val in sorted(cand_fracs.items()):
            comp = [(r["_round"], fr[name])
                    for r, fr in hist_fracs if name in fr]
            mkey = f"device.{name}.bytes_frac"
            if not comp:
                out["metrics"][mkey] = {
                    "skipped": "no comparable schema-3 round"}
                continue
            entry = _margin_entry(val, comp, True,
                                  spread_mult=spread_mult,
                                  min_margin=min_margin)
            out["metrics"][mkey] = entry
            out["gated_metrics"].append(mkey)
            if not entry["ok"]:
                out["ok"] = False
        # a fraction history published that the candidate DROPPED must
        # not pass silently — vanishing entirely is the limit of
        # "silently sinking".  A candidate publishing no fractions at
        # all skips instead (unknown-peak backend or cost analysis
        # unavailable wholesale: a platform difference, not a phase
        # regression).
        for name in sorted({n for _, fr in hist_fracs for n in fr}):
            if name in cand_fracs:
                continue
            mkey = f"device.{name}.bytes_frac"
            if not cand_fracs:
                out["metrics"][mkey] = {
                    "skipped": "candidate publishes no fractions"}
                continue
            base_round, baseline = [(r["_round"], fr[name])
                                    for r, fr in hist_fracs
                                    if name in fr][-1]
            out["metrics"][mkey] = {
                "candidate": None, "baseline": baseline,
                "baseline_round": base_round, "direction": "higher",
                "ok": False,
                "error": "fraction published by a committed round is "
                         "absent from the candidate",
            }
            out["gated_metrics"].append(mkey)
            out["ok"] = False

    # -- client-contract hard pins (PR 15): the retrace-red pattern ----------
    # Contract-drill receipts (tools/contract_drill.py) are ROBUSTNESS
    # artifacts: they carry no comparable throughput metric and must
    # never be throughput-gated — but a committed receipt claiming
    # `duplicate_acks > 0`, `lost_acks > 0` or `linearizable == false`
    # is a hard red with no margin: each is a count/verdict of a
    # correctness hazard, not a wall.
    if cand.get("metric") in ("contract_drill", "failover_drill",
                              "partition_drill", "multihost_drill",
                              "hostfail_drill") \
            or "duplicate_acks" in cand or "linearizable" in cand \
            or "fenced_acks_merged" in cand \
            or "unadopted_dead_hosts" in cand:
        # partition-drill pins (PR 18) ride the same marginless rule:
        # a merged fenced ack or an unrepaired diverged follower is a
        # split-brain/divergence verdict, not a wall; the multihost
        # drill (PR 19) adds rpo_ops — an acked op missing after
        # union recovery is lost durability, not a slow number; the
        # hostfail drill (PR 20) adds unadopted_dead_hosts — an
        # expired host nobody adopted is unavailability, not a wall
        for name in ("duplicate_acks", "lost_acks", "rpo_ops",
                     "fenced_acks_merged",
                     "diverged_followers_unrepaired",
                     "unadopted_dead_hosts"):
            val = cand.get(name)
            if val is None:
                continue
            cok = int(val) == 0
            out["metrics"][f"contract.{name}"] = {
                "candidate": int(val), "baseline": 0,
                "direction": "zero", "ok": cok}
            out["gated_metrics"].append(f"contract.{name}")
            if not cok:
                out["ok"] = False
        lin = cand.get("linearizable")
        if lin is not None:
            lok = bool(lin)
            out["metrics"]["contract.linearizable"] = {
                "candidate": lok, "baseline": True,
                "direction": "true", "ok": lok}
            out["gated_metrics"].append("contract.linearizable")
            if not lok:
                out["ok"] = False
        # a contract receipt is judged by its pins, not by throughput
        # comparability: clear the no-comparable-metric error (which
        # would exit 2) and let the pins decide pass/red.  Other
        # robustness receipts (reshard/recovery) still exit 2 here by
        # design — they carry no gateable claim at all.
        contract_gates = [m for m in out["gated_metrics"]
                          if m.startswith("contract.")]
        if out.get("error") and contract_gates:
            out.pop("error")
            out["ok"] = all(out["metrics"][m]["ok"]
                            for m in out["gated_metrics"]
                            if "ok" in out["metrics"][m])

    # -- lint provenance (PR 9): warn, never gate --------------------------
    # bench.py stamps config.lint_clean (shermanlint verdict of the tree
    # the receipt ran from; optional — older schemas lack it).  A False
    # means the number came from a convention-violating tree: worth an
    # asterisk next to the receipt, but walls are walls — lint hygiene
    # must not mask or manufacture a perf regression.
    lint = (cand.get("config") or {}).get("lint_clean")
    if lint is False:
        out.setdefault("warnings", []).append(
            "receipt produced from a tree WITH shermanlint findings "
            "(config.lint_clean=false) — re-run `python "
            "tools/shermanlint.py` and re-capture before committing")
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="noise-aware perf-regression gate over BENCH_r*.json")
    ap.add_argument("--receipt", required=True,
                    help="fresh bench JSON (bare line or driver-wrapped)")
    ap.add_argument("--repo", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="directory holding the committed BENCH_r*.json trajectory")
    ap.add_argument("--spread-mult", type=float, default=2.0,
                    help="margin = max(min-margin, mult x spread)")
    ap.add_argument("--min-margin", type=float, default=0.10,
                    help="floor on the relative regression margin")
    ap.add_argument("--json", action="store_true",
                    help="print the receipt JSON only (no prose line)")
    a = ap.parse_args(argv)

    try:
        cand = load_receipt(a.receipt)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(json.dumps({"metric": "perfgate", "ok": False,
                          "error": f"unreadable receipt: {e}"}))
        return 2
    rounds = load_trajectory(a.repo)
    if not rounds:
        print(json.dumps({"metric": "perfgate", "ok": False,
                          "error": f"no BENCH_r*.json under {a.repo}"}))
        return 2
    res = gate(cand, rounds, spread_mult=a.spread_mult,
               min_margin=a.min_margin)
    print(json.dumps(res))
    if not a.json:
        for w in res.get("warnings", ()):
            print(f"# WARNING: {w}", file=sys.stderr)
        for n, d in res["metrics"].items():
            if "ratio" in d:
                print(f"# {n}: {d['candidate']:.6g} vs r"
                      f"{d['baseline_round']} {d['baseline']:.6g} "
                      f"(ratio {d['ratio']}, margin {d['margin']}, "
                      f"{'ok' if d['ok'] else 'REGRESSION'})",
                      file=sys.stderr)
            elif "error" in d:  # vanished device fraction
                print(f"# {n}: {d['error']} (baseline r"
                      f"{d['baseline_round']} {d['baseline']:.6g}, "
                      "REGRESSION)", file=sys.stderr)
            elif "ok" in d:  # marginless hard gates (device.retraces)
                print(f"# {n}: {d['candidate']} (must be "
                      f"{d['baseline']}, "
                      f"{'ok' if d['ok'] else 'REGRESSION'})",
                      file=sys.stderr)
            else:
                print(f"# {n}: skipped ({d['skipped']})", file=sys.stderr)
        print("PERFGATE " + ("PASS" if res["ok"] else "FAIL"),
              file=sys.stderr)
    if "error" in res:
        return 2
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
