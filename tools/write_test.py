#!/usr/bin/env python
"""Write-amplification probe — ``test/write_test.cpp`` parity.

Performs N random inserts then dumps the DSM op counters (read/write/cas
counts and bytes, ``DSM.cpp:17-21`` / ``write_test.cpp:66-77``) plus
per-op write amplification.  The reference's point: Sherman's single-entry
write-back means a non-split insert writes ONE leaf entry + versions, not
a full 1 KB page — the counters prove the same holds here.

    python tools/write_test.py [kNodeCount] [--n N]
"""

from __future__ import annotations

import argparse

import numpy as np

from common import build_cluster, pages_for_keys, setup_platform


def main(argv=None) -> None:
    p = argparse.ArgumentParser()
    p.add_argument("kNodeCount", type=int, nargs="?", default=1)
    p.add_argument("--n", type=int, default=200_000)
    p.add_argument("--batch", type=int, default=16_384)
    a = p.parse_args(argv)
    setup_platform(a.kNodeCount)

    from sherman_tpu.models import batched
    from sherman_tpu.utils import Timer, notify_info

    n_nodes = a.kNodeCount
    cluster, tree, eng = build_cluster(
        n_nodes, max(4096, pages_for_keys(a.n) // n_nodes),
        a.batch // n_nodes)
    dsm = tree.dsm

    rng = np.random.default_rng(11)
    keys = np.unique(rng.integers(1, 1 << 62, int(a.n * 1.1),
                                  dtype=np.uint64))[:a.n]
    # seed the tree so inserts exercise the non-split fast path, then
    # measure a fresh upsert pass over every key
    batched.bulk_load(tree, keys, keys)

    # exact read-accounting parity (DSM.cpp:17-21 counter semantics): on
    # a quiescent tree a routerless descent costs exactly one page read
    # per level per key — (height+1) loop reads + 1 final leaf gather
    sample = keys[:2048]
    c0 = dsm.counter_snapshot()
    got, found = eng.search(sample)
    assert bool(found.all())
    c1 = dsm.counter_snapshot()
    reads = c1["read_ops"] - c0["read_ops"]
    expect = sample.size * (tree._root_level + 2)
    assert reads == expect, f"read accounting drift: {reads} != {expect}"
    print(f"read accounting parity: {reads:,} reads for {sample.size:,} "
          f"keys at height {tree._root_level} (exact)")

    eng.attach_router()
    base = dsm.counter_snapshot()

    t = Timer()
    t.begin()
    st = eng.insert(keys, keys * np.uint64(7))
    ns = t.end()
    now = dsm.counter_snapshot()
    delta = {k: now[k] - base[k] for k in now}
    n_ops = len(keys)
    notify_info("%d upserts in %.2fs (%.2f M ops/s), host_path=%d",
                n_ops, ns / 1e9, n_ops / (ns / 1e9) / 1e6, st["host_path"])
    print("op counters (delta):")
    for k, v in delta.items():
        print(f"  {k:>16}: {v:>14,}")
    wa_bytes = delta["write_bytes"] / max(n_ops, 1)
    print(f"  write amplification: {wa_bytes:.1f} B/insert "
          f"(full-page rewrite would be 1024 B)")
    got, found = eng.search(keys[: 4096])
    assert found.all() and (got == keys[:4096] * np.uint64(7)).all()
    ns = tree.lock_bench(17, loops=16)  # Tree.cpp:310-321 micro-hook
    print(f"lock_bench: {ns / 1e3:.1f} us/lock-unlock round trip")
    print("write_test PASS")


if __name__ == "__main__":
    main()
