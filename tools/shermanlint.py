#!/usr/bin/env python
"""shermanlint — run the repo's invariant checker.

Usage::

    python tools/shermanlint.py sherman_tpu/ tools/ bench.py
    python tools/shermanlint.py --json ...            # machine-readable
    python tools/shermanlint.py --write-baseline ...  # grandfather now
    python tools/shermanlint.py --no-baseline ...     # raw findings

Exit codes: 0 clean, 1 findings, 2 infrastructure error (stale
baseline entry, malformed pragma, unreadable baseline).  Stale
baseline entries are ERRORS by design — a baseline that rots keeps
suppressing whatever new violation drifts onto its line.

The rule set, registries, and suppression pragma grammar live in
``sherman_tpu/analysis/``; the README "Static analysis" section has
the rule catalog and the lesson each rule encodes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

DEFAULT_BASELINE = REPO / ".shermanlint-baseline.json"
DEFAULT_PATHS = ["sherman_tpu/", "tools/", "bench.py"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="AST invariant checker for the sherman_tpu repo")
    ap.add_argument("paths", nargs="*", default=None,
                    help=f"files/dirs to lint (default: {DEFAULT_PATHS})")
    ap.add_argument("--baseline", default=str(DEFAULT_BASELINE),
                    help="baseline file (default: %(default)s)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline; report raw findings")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings as the new baseline "
                         "(bootstrap path for a new rule; the committed "
                         "target is an empty baseline)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="one JSON object on stdout")
    ap.add_argument("--quiet", action="store_true",
                    help="findings only, no summary line")
    args = ap.parse_args(argv)

    os.chdir(REPO)  # registry patterns + README lookup are repo-relative
    from sherman_tpu import analysis

    paths = args.paths or DEFAULT_PATHS
    baseline = None
    if not args.no_baseline and not args.write_baseline:
        try:
            baseline = analysis.load_baseline(args.baseline)
        except analysis.BaselineError as e:
            print(f"shermanlint: {e}", file=sys.stderr)
            return 2

    res = analysis.run(paths, baseline=baseline, root=REPO)

    if args.write_baseline:
        analysis.write_baseline(args.baseline, res.findings)
        print(f"shermanlint: wrote {len(res.findings)} entries to "
              f"{args.baseline}")
        return 0

    if args.as_json:
        print(json.dumps({
            "clean": res.clean,
            "files_checked": res.files_checked,
            "findings": [f.__dict__ for f in res.findings],
            "pragma_errors": [f.__dict__ for f in res.pragma_errors],
            "baseline_errors": res.baseline_errors,
            "suppressed": len(res.suppressed),
            "baselined": len(res.baselined),
        }, indent=1))
    else:
        for f in res.findings:
            print(f.render())
        for f in res.pragma_errors:
            print(f.render())
        for msg in res.baseline_errors:
            print(f"ERROR: {msg}")
        if not args.quiet:
            print(f"shermanlint: {res.files_checked} files, "
                  f"{len(res.findings)} finding(s), "
                  f"{len(res.suppressed)} suppressed, "
                  f"{len(res.baselined)} baselined, "
                  f"{len(res.pragma_errors)} pragma error(s), "
                  f"{len(res.baseline_errors)} baseline error(s)")

    if res.baseline_errors or res.pragma_errors:
        return 2
    return 0 if not res.findings else 1


if __name__ == "__main__":
    sys.exit(main())
