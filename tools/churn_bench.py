#!/usr/bin/env python
"""Drifting-keyspace churn + reclamation benchmark (real chip).

The workload empty-leaf reclamation exists for: a sliding key window —
each iteration inserts a fresh window of keys at the right edge and
deletes the oldest window at the left — on a BOUNDED pool.  The
reference leaks the pool dry here (``free()`` is a no-op,
``DSM.h:226``); sherman_tpu's reclaim pass (unlink + parent cleanup +
quarantine + free, ``BatchedEngine.reclaim_empty_leaves``) runs INSIDE
the timed loop and must keep occupancy FLAT.

Prints per-iteration pool telemetry and ONE final JSON line:
churn ops/s (inserts + deletes, reclaim passes included in the wall
clock), reclaim pass cost, pool occupancy first/last/max, parked-page
count, and end-of-run integrity (live window searched, structure
checked).

Control: ``--no-reclaim`` runs the same loop without reclaim passes —
on the default sizing the pool exhausts within a few iterations
(MemoryError), which is the reference's fate on this workload.

Steady state needs DENSITY-MATCHED warm data: churn-inserted leaves
hold ~LEAF_CAP/2 keys (append-split density), so bulk-load at
``--fill 0.5`` or warm leaves (denser) retire SLOWER than inserts
create new ones and live pages grow structurally — ~window/7 pages per
iteration at the default fill 0.75 — until the delete window reaches
the churned region, regardless of reclaim.

Run (real chip):  python tools/churn_bench.py --keys 10000000
                      --window 524288 --reclaim-every 1 --fill 0.5 \\
                      --minutes 32
CPU smoke:        SHERMAN_PLATFORM=cpu python tools/churn_bench.py \\
                      --keys 60000 --window 4000 --iters 8 --chunk 8192
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from common import setup_platform  # noqa: E402


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=10_000_000,
                    help="live keys at any moment (the sliding window "
                         "set's size)")
    ap.add_argument("--window", type=int, default=524_288,
                    help="keys inserted + deleted per iteration")
    ap.add_argument("--iters", type=int, default=55)
    ap.add_argument("--chunk", type=int, default=131_072,
                    help="engine call width.  Fresh-window inserts all "
                         "land on the current RIGHTMOST leaf (appending "
                         "churn), so each chunk needs a full split "
                         "cascade: ~log2(chunk/LEAF_CAP) doubling "
                         "rounds.  Size chunks so that cascade fits the "
                         "round budget (--max-rounds) with margin — a "
                         "chunk that exhausts its rounds spills the "
                         "tail to the per-key host path (~50 ms/key "
                         "over an access tunnel)")
    ap.add_argument("--max-rounds", type=int, default=24,
                    help="insert round budget per chunk (the appending "
                         "cascade needs ~log2(chunk/49) split rounds "
                         "plus retry slack; the engine default 16 is "
                         "sized for scattered inserts)")
    ap.add_argument("--reclaim-every", type=int, default=2,
                    help="reclaim pass cadence (iterations)")
    ap.add_argument("--fill", type=float, default=0.75)
    ap.add_argument("--slack", type=float, default=0.55,
                    help="pool slack over the warm tree, in units of "
                         "window-leaf footprints: sized so the loop "
                         "EXHAUSTS without reclaim but runs flat with "
                         "it (unlink + quarantine hold "
                         "~3*reclaim_every+2 windows in flight — see "
                         "the sizing comment in main)")
    ap.add_argument("--streams", type=int, default=0,
                    help="append streams (0 = auto: window/128, capped "
                         "4096).  The churn keyspace is a multi-stream "
                         "time series: key = (stream << 44) | seq, so a "
                         "window's inserts append at --streams points "
                         "of the tree instead of one.  A SINGLE append "
                         "point is pathological for a batched engine: "
                         "every key targets the one rightmost leaf, "
                         "which absorbs ~LEAF_CAP/2 winners per round "
                         "and splits again — ~25 keys/round measured "
                         "on chip, i.e. linear rounds in window size "
                         "(the split does not bisect PENDING keys: "
                         "they are all above the split key).  Deletes "
                         "still retire whole leaves per stream, which "
                         "is what reclaim needs")
    ap.add_argument("--no-reclaim", action="store_true",
                    help="control: reference behavior (pool leaks)")
    ap.add_argument("--minutes", type=float, default=0.0,
                    help="if > 0, keep iterating until this much wall "
                         "time has passed (overrides --iters)")
    args = ap.parse_args(argv)

    jax = setup_platform(1)
    jax.config.update("jax_compilation_cache_dir", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from sherman_tpu.cluster import Cluster
    from sherman_tpu.config import LEAF_CAP, DSMConfig
    from sherman_tpu.models import batched
    from sherman_tpu.models.btree import Tree

    S = args.streams or max(16, min(4096, args.window // 128))

    def key_of(i):
        """Multi-stream time-series keyspace (see --streams)."""
        i = np.asarray(i, np.uint64)
        return ((i % np.uint64(S)) << np.uint64(44)) \
            | ((i // np.uint64(S)) + np.uint64(1))

    vals_of = lambda k: k ^ np.uint64(0xBEEF)

    # pool sizing: warm leaves + internals + a bounded number of
    # window-leaf footprints.  In-flight retired pages before the first
    # release: a deleted window is UNLINKED one reclaim pass after its
    # delete (the chain scan sees it empty then), then sits quarantined
    # for ~2 passes (engine default) — with passes every
    # ``reclaim_every`` iters that is ~(3 * reclaim_every + 1) windows
    # of lag, +1 window for the alternate-pair drain (a pass unlinks at
    # most every other member of an empty run).
    per_leaf = max(1, int(LEAF_CAP * args.fill))
    warm_pages = int(args.keys / per_leaf * 1.06) + 2048
    win_pages = int(args.window / (LEAF_CAP // 2))
    slack_pages = int(win_pages * (3 * args.reclaim_every + 2)
                      * (1.0 + args.slack))
    pages = warm_pages + slack_pages
    # locks_per_node sized for the reclaim batches: a pass's candidate
    # set (10^4-10^5 pairs under churn backlog) CAS-locks pages through
    # the hashed lock table, and pairs hashing onto an already-taken
    # word defer to the next pass — at 65,536 words the birthday
    # collisions capped unlinks ~15% under the retire rate and the pool
    # leaked ~3K pages/iter until exhaustion.  1M words (4 MB) keeps
    # the deferral rate negligible.
    cfg = DSMConfig(machine_nr=1, pages_per_node=pages,
                    locks_per_node=1 << 20, step_capacity=args.chunk,
                    chunk_pages=1024, host_step_capacity=8192)
    cluster = Cluster(cfg)
    tree = Tree(cluster)
    eng = batched.BatchedEngine(tree, batch_per_node=args.chunk,
                                split_slots=min(131_072, win_pages * 2))
    eng.parent_flush_threshold = eng.split_slots

    rng = np.random.default_rng(23)
    warm = np.sort(key_of(np.arange(args.keys, dtype=np.uint64)))
    t0 = time.time()
    batched.bulk_load(tree, warm, vals_of(warm), fill=args.fill)
    router = eng.attach_router()
    print(f"# warm load {time.time() - t0:.1f}s pool={pages} pages "
          f"(warm ~{warm_pages}, slack {slack_pages}) streams={S} "
          f"router_lb={router.lb}", file=sys.stderr)

    def pool_live():
        used = free = 0
        for d in cluster.directories:
            used += d.allocator.pages_used
            free += d.allocator.pages_free
        return used - free, free

    # compile warmup outside the timed loop: one small insert (split
    # kernels), one small delete, one reclaim pass
    w = min(16_384, args.window)
    wf = key_of(np.arange(args.keys, args.keys + w, dtype=np.uint64))
    eng.insert(wf, vals_of(wf))
    eng.delete(wf)
    if not args.no_reclaim:
        eng.reclaim_empty_leaves()

    lo, hi = 0, args.keys
    live0, _ = pool_live()
    occ = [live0]
    parked_hist = [len(eng._reclaim_state["parked"])]
    reclaim_ms = []
    reclaim_stats = {"unlinked": 0, "freed": 0}
    n_ops = 0
    t_start = time.time()
    it = 0
    while True:
        if args.minutes > 0:
            if time.time() - t_start > args.minutes * 60:
                break
        elif it >= args.iters:
            break
        fresh = key_of(np.arange(hi, hi + args.window, dtype=np.uint64))
        for i in range(0, fresh.size, args.chunk):
            # ascending chunks; shuffle WITHIN a chunk (arrival order
            # uncorrelated with key order, as in the storm driver) but
            # keep chunks ordered so each cascade builds on the last
            ck = fresh[i: i + args.chunk].copy()
            rng.shuffle(ck)
            t_c = time.time()
            st_i = eng.insert(ck, vals_of(ck), max_rounds=args.max_rounds)
            print(f"#     ins chunk {i // args.chunk} "
                  f"{time.time() - t_c:.1f}s rounds={st_i['rounds']} "
                  f"host={st_i['host_path']}", file=sys.stderr, flush=True)
            if st_i["host_path"] > args.chunk // 100:
                print(f"# WARN iter {it}: {st_i['host_path']} keys "
                      f"spilled to the host path (cascade exceeded "
                      f"--max-rounds?)", file=sys.stderr)
        dead = key_of(np.arange(lo, lo + args.window, dtype=np.uint64))
        for i in range(0, dead.size, args.chunk):
            t_c = time.time()
            eng.delete(dead[i: i + args.chunk])
            print(f"#     del chunk {i // args.chunk} "
                  f"{time.time() - t_c:.1f}s", file=sys.stderr, flush=True)
        n_ops += fresh.size + dead.size
        lo += args.window
        hi += args.window
        if not args.no_reclaim and it % args.reclaim_every == \
                args.reclaim_every - 1:
            t1 = time.time()
            st = eng.reclaim_empty_leaves()
            reclaim_ms.append((time.time() - t1) * 1e3)
            reclaim_stats["unlinked"] += st["unlinked"]
            reclaim_stats["freed"] += st["freed"]
            print(f"#     reclaim {reclaim_ms[-1] / 1e3:.1f}s "
                  f"unlinked={st['unlinked']} freed={st['freed']} "
                  f"quarantined={st['quarantined']} "
                  f"candidates={st['candidates']}",
                  file=sys.stderr, flush=True)
        live, free = pool_live()
        occ.append(live)
        parked_hist.append(len(eng._reclaim_state["parked"]))
        it += 1
        dt = time.time() - t_start
        print(f"#   iter {it}: {n_ops / dt / 1e3:.1f} K ops/s cum, "
              f"pool live {live} (free {free}), "
              f"parked {parked_hist[-1]}, "
              f"reclaimed {reclaim_stats['freed']}", file=sys.stderr)
    elapsed = time.time() - t_start

    # integrity: current window fully live, dead band gone, structure ok
    print(f"# verify: probing live window + structure", file=sys.stderr,
          flush=True)
    t_v = time.time()
    live_keys = key_of(np.arange(lo, hi, dtype=np.uint64))
    probe = live_keys[:: max(1, live_keys.size // 50_000)]
    got, found = eng.search(probe)
    assert found.all(), f"churn lost {int((~found).sum())} live keys"
    np.testing.assert_array_equal(got, vals_of(probe))
    old_probe = key_of(np.arange(max(0, lo - args.window), lo,
                                 dtype=np.uint64))[:10_000]
    _, f2 = eng.search(old_probe)
    assert not f2.any(), "deleted window still resolves"
    # whole-pool structure check on DEVICE (models/validate.py): the
    # host walker costs 30+ minutes at 10^5-page scale over an access
    # tunnel, the jitted validator seconds
    from sherman_tpu.models.validate import check_structure_device
    info = check_structure_device(tree)
    # exact count: the validator's device-side key total must equal the
    # live window EXACTLY — catches any lost or duplicated key the
    # sampled probes above could miss, at zero extra device cost
    assert info["keys"] == hi - lo, \
        f"device key count {info['keys']} != live window {hi - lo}"
    print(f"# verify done in {time.time() - t_v:.1f}s: {info}",
          file=sys.stderr, flush=True)

    out = {
        "metric": "churn_reclaim",
        "value": round(n_ops / elapsed),
        "unit": "ops/s",
        "churn_ops_s": round(n_ops / elapsed),
        "iters": it,
        "elapsed_s": round(elapsed, 1),
        "window": args.window,
        "keys_live": args.keys,
        "pool_pages": pages,
        "pool_live_first": occ[1] if len(occ) > 1 else occ[0],
        "pool_live_last": occ[-1],
        "pool_live_max": max(occ),
        # flat = the steady-state band is bounded: growth since the
        # first full unlink->quarantine->release cycle stays within the
        # in-flight window footprint (see the slack sizing comment)
        # plus chunk-lease granularity (the allocator bumps whole
        # chunk_pages leases, so occupancy moves in those steps).  The
        # baseline clamps to the run's midpoint so short runs (CI
        # smoke) still compare two distinct samples instead of
        # degenerating to occ[-1] - occ[-1].
        "pool_flat": bool(
            occ[-1] - occ[max(1, min(len(occ) - 1,
                                     3 * args.reclaim_every + 1,
                                     (len(occ) - 1) // 2))]
            <= (3 * args.reclaim_every + 2) * win_pages
            + 2 * cfg.chunk_pages),
        "parked_final": parked_hist[-1],
        "reclaim_passes": len(reclaim_ms),
        "reclaim_ms_mean": round(float(np.mean(reclaim_ms)), 1)
        if reclaim_ms else None,
        "reclaim_ms_max": round(float(np.max(reclaim_ms)), 1)
        if reclaim_ms else None,
        "unlinked": reclaim_stats["unlinked"],
        "freed": reclaim_stats["freed"],
        "tree_keys": info["keys"],
        "no_reclaim": args.no_reclaim,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
