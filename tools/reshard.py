#!/usr/bin/env python
"""Elastic cluster resize CLI — rewrite a checkpoint for a new node count.

    python tools/reshard.py <src.npz> <dst.npz> --nodes M [--hosts H]
        [--pages-per-node P] [--locks-per-node L]

Offline transform (numpy only, no devices needed): repacks the live pages
of an N-node checkpoint onto M nodes and rewrites every packed address
(internal entries, sibling links, root meta) through the old->new map.
See sherman_tpu/utils/reshard.py for the mechanics.  Restore the output
with utils.checkpoint.restore on an M-node mesh (H processes when
--hosts H > 1).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> dict:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("src")
    p.add_argument("dst")
    p.add_argument("--nodes", type=int, required=True,
                   help="target machine_nr")
    p.add_argument("--hosts", type=int, default=1,
                   help="emit multi-host format for this many processes")
    p.add_argument("--pages-per-node", type=int, default=None)
    p.add_argument("--locks-per-node", type=int, default=None)
    a = p.parse_args(argv)

    from sherman_tpu.utils.reshard import reshard
    out = reshard(a.src, a.dst, a.nodes, pages_per_node=a.pages_per_node,
                  locks_per_node=a.locks_per_node, hosts=a.hosts)
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
