#!/usr/bin/env python
"""Checkpoint/restore wall times at benchmark scale (real chip).

Builds the north-star tree (default 100 M synthetic keys, the bench.py
config), then times one full cycle: ``checkpoint(cluster, path)`` ->
``restore(path)`` -> post-restore verification (a key sample searched
through a fresh engine + the device structure validator).  With
``--delta-ops N`` (default on) it also measures the INCREMENTAL side:
N engine upserts after the base, one ``checkpoint_delta`` (only the
dirty pages), and a chain restore — the delta-vs-full A/B the recovery
plane's "cheap frequent deltas" claim rests on.  Prints a side-by-side
table on stderr and ONE JSON line (receipt) with all wall times/sizes.

The reference has no durability story at any scale (SURVEY.md §5); this
pins the cost of ours at the full benchmark config, where the pool is
multi-GB — a full checkpoint is one d2h of the sharded pool + tiny
metadata, a delta only the written pages.  On this environment both
transfers ride the access tunnel; the JSON publishes byte sizes so a
co-located host can be priced from its own link rate.

It also prices the journal's **group-commit A/B** (round-8): per-op
fsync vs ``Journal(sync=True, group_commit_ms=...)`` under a
multi-writer append load shaped like the recovery drill's batch
records — acks/s, mean/p99 ack latency, the added ack latency vs the
per-op baseline, and the measured acks-per-fsync coalescing ratio
(asserted >= 2x at ``group_commit_ms=2`` — the receipt the pipelined
write path's "writes ride the group commit" claim rests on; RPO 0
itself is pinned by the recovery drill, which runs with the knob on).

Run (real chip):  python tools/ckpt_bench.py --keys 100000000
CPU smoke:        SHERMAN_PLATFORM=cpu python tools/ckpt_bench.py \\
                      --keys 50000 --sample 5000 --delta-ops 4000
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from common import setup_platform  # noqa: E402


def journal_group_commit_ab(threads: int = 4, appends: int = 24,
                            rows: int = 256,
                            modes=(0.0, 0.5, 2.0)) -> dict:
    """The group-commit A/B: ``threads`` concurrent writers each
    appending ``appends`` drill-shaped batch records (``rows`` u64
    key/value pairs — the recovery drill's record scale) through one
    Journal per mode.  Every append blocks until its record is covered
    by an fsync (RPO 0 in every mode); the A/B prices what that ack
    costs: per-op fsync re-serializes the writers on the fsync
    latency, group commit coalesces a window of acks into one fsync.
    Returns {mode_label: {acks_per_s, ack_mean_ms, ack_p99_ms,
    added_ack_ms, fsyncs, acks_per_fsync}}."""
    import shutil
    import tempfile
    import threading

    from sherman_tpu import obs
    from sherman_tpu.utils import journal as J

    td = tempfile.mkdtemp(prefix="sherman_jab_")
    rng = np.random.default_rng(17)
    # one key/value block per (thread, append): identical across modes
    # so the three files carry the same bytes
    blocks = rng.integers(1, 1 << 60, (threads, appends, rows),
                          dtype=np.uint64)
    results: dict = {}
    try:
        for gc in modes:
            label = "per_op" if gc == 0 else f"gc_{gc:g}ms"
            path = os.path.join(td, f"{label}.wal")
            snap0 = obs.snapshot()
            j = J.Journal(path, sync=True, group_commit_ms=gc)
            lat: list = []
            lock = threading.Lock()

            def writer(t):
                mine = []
                for i in range(appends):
                    ks = blocks[t, i]
                    t0 = time.perf_counter()
                    j.append(J.J_UPSERT, ks, ks ^ np.uint64(0x5EED))
                    mine.append(time.perf_counter() - t0)
                with lock:
                    lat.extend(mine)

            ths = [threading.Thread(target=writer, args=(t,))
                   for t in range(threads)]
            t0 = time.perf_counter()
            for t in ths:
                t.start()
            for t in ths:
                t.join()
            elapsed = time.perf_counter() - t0
            j.close()
            d = obs.delta(snap0, obs.snapshot())
            n = threads * appends
            assert len(J.read_records(path)) == n, \
                "group-commit A/B lost records"
            fsyncs = int(d.get("journal.fsyncs", 0))
            lat.sort()
            results[label] = {
                "group_commit_ms": gc,
                "acks": n,
                "acks_per_s": round(n / elapsed, 1),
                "ack_mean_ms": round(1e3 * sum(lat) / len(lat), 3),
                "ack_p99_ms": round(
                    1e3 * lat[int(0.99 * (len(lat) - 1))], 3),
                "fsyncs": fsyncs,
                "acks_per_fsync": round(n / max(1, fsyncs), 2),
            }
            os.unlink(path)
    finally:
        # a failed mode leaves its .wal behind: remove the whole
        # tempdir, contents and all
        shutil.rmtree(td, ignore_errors=True)
    base = results.get("per_op", {}).get("ack_mean_ms", 0.0)
    for r in results.values():
        # the group-commit tradeoff, made explicit: acks coalesce at
        # the cost of up to group_commit_ms of added ack latency
        r["added_ack_ms"] = round(r["ack_mean_ms"] - base, 3)
    return results


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=100_000_000)
    ap.add_argument("--sample", type=int, default=200_000,
                    help="post-restore verification sample size")
    ap.add_argument("--dir", default=None,
                    help="where to write the .npz (default: a tempdir; "
                         "the 100 M-key pool is ~4.3 GB on disk)")
    ap.add_argument("--validate", action="store_true",
                    help="run the whole-pool device validator on the "
                         "restored tree too (adds its own wall time)")
    ap.add_argument("--delta-ops", type=int, default=None,
                    help="engine upserts between base and delta "
                         "checkpoint (default keys/100 capped at 1 M; "
                         "0 disables the delta A/B)")
    ap.add_argument("--journal-ab-threads", type=int, default=4,
                    help="concurrent writers in the journal "
                         "group-commit A/B (0 disables it)")
    ap.add_argument("--journal-ab-appends", type=int, default=24,
                    help="records per writer in the group-commit A/B")
    args = ap.parse_args(argv)
    if args.delta_ops is None:
        args.delta_ops = min(max(args.keys // 100, 1000), 1_000_000)

    jax = setup_platform(1)
    jax.config.update("jax_compilation_cache_dir", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from sherman_tpu import native
    from sherman_tpu.cluster import Cluster
    from sherman_tpu.config import LEAF_CAP, DSMConfig
    from sherman_tpu.models import batched
    from sherman_tpu.models.btree import Tree
    from sherman_tpu.utils import checkpoint as CK

    fill = 0.75
    per_leaf = max(1, int(LEAF_CAP * fill))
    est_pages = int(args.keys / per_leaf * 1.10) + 8192
    pages = 1 << max(14, (est_pages - 1).bit_length())
    cfg = DSMConfig(machine_nr=1, pages_per_node=pages,
                    locks_per_node=65_536, step_capacity=65_536,
                    chunk_pages=4096)
    cluster = Cluster(cfg)
    tree = Tree(cluster)

    salt = 0x5E17_AB1E_5A17
    if native.available():
        keys, _ = native.synthetic_keyspace(args.keys, salt)
    else:
        rng = np.random.default_rng(7)
        keys = np.unique(rng.integers(1, 1 << 63, int(args.keys * 1.05),
                                      dtype=np.uint64))[: args.keys]
    vals = keys ^ np.uint64(0xDEADBEEF)
    t0 = time.time()
    batched.bulk_load(tree, keys, vals, fill=fill)
    build_s = time.time() - t0
    print(f"# bulk_load {build_s:.1f}s ({args.keys} keys, pool {pages} "
          f"pages)", file=sys.stderr, flush=True)

    td = args.dir or tempfile.mkdtemp(prefix="sherman_ckpt_")
    path = os.path.join(td, "bench.npz")
    dpath = os.path.join(td, "bench.delta1.npz")
    delta = None
    try:
        t0 = time.time()
        epoch = CK.checkpoint(cluster, path)
        ckpt_s = time.time() - t0
        size = os.path.getsize(path)
        print(f"# checkpoint {ckpt_s:.1f}s ({size / 1e9:.2f} GB)",
              file=sys.stderr, flush=True)

        # delta A/B: N engine upserts dirty a bounded page set; the
        # delta saves ONLY those pages — the "cheap frequent deltas"
        # half of the recovery plane, priced at this scale
        dkeys = None
        if args.delta_ops:
            # traffic-engine batch sized to the op count: the CPU smoke
            # then compiles a small insert program, the chip run a real
            # one
            eng0 = batched.BatchedEngine(
                tree, batch_per_node=min(65_536,
                                         max(1024, args.delta_ops)))
            eng0.attach_router()
            # a CLUSTERED working set (contiguous key range): the delta
            # then covers the touched leaves, not every leaf — a uniform
            # spray of N ops over N*40 keys would dirty the whole tree
            # and measure nothing but a full save with extra steps
            dkeys = keys[: min(args.delta_ops, args.keys)]
            t0 = time.time()
            st = eng0.insert(dkeys, dkeys ^ np.uint64(0x5EED))
            traffic_s = time.time() - t0
            assert st["lock_timeouts"] == 0
            t0 = time.time()
            dinfo = CK.checkpoint_delta(cluster, dpath,
                                        parent_epoch=epoch)
            delta = {"ops": int(dkeys.size),
                     "traffic_s": round(traffic_s, 1),
                     "pages": dinfo["pages"],
                     "npz_bytes": dinfo["bytes"],
                     "checkpoint_s": round(time.time() - t0, 2)}
            print(f"# delta checkpoint {delta['checkpoint_s']}s "
                  f"({delta['pages']} pages, "
                  f"{delta['npz_bytes'] / 1e6:.1f} MB)",
                  file=sys.stderr, flush=True)

        # release the ORIGINAL pool before restoring: at the 100 M-key
        # config two resident pools (4.3 GB each) plus the validator's
        # intermediates exhaust a 16 GB chip
        mesh = cluster.dsm.mesh
        cluster.dsm.pool.delete()
        del tree
        t0 = time.time()
        c2 = CK.restore_chain(path, [dpath] if delta else [], mesh=mesh)
        restore_s = time.time() - t0
        print(f"# restore {restore_s:.1f}s"
              + (" (chain: base + 1 delta)" if delta else ""),
              file=sys.stderr, flush=True)

        t2 = Tree(c2)
        e2 = batched.BatchedEngine(t2, batch_per_node=65_536)
        e2.attach_router()
        t0 = time.time()
        idx = np.linspace(0, args.keys - 1,
                          min(args.sample, args.keys)).astype(np.int64)
        probe = keys[idx]
        got, found = e2.search(probe)
        assert found.all(), f"restore lost {int((~found).sum())} keys"
        if dkeys is not None:
            # delta-written values win where the probe overlaps them
            upd = np.isin(probe, dkeys)
            np.testing.assert_array_equal(
                got[upd], probe[upd] ^ np.uint64(0x5EED))
            np.testing.assert_array_equal(
                got[~upd], probe[~upd] ^ np.uint64(0xDEADBEEF))
            gd, fd = e2.search(dkeys)
            assert fd.all()
            np.testing.assert_array_equal(gd, dkeys ^ np.uint64(0x5EED))
        else:
            np.testing.assert_array_equal(got,
                                          probe ^ np.uint64(0xDEADBEEF))
        verify_s = time.time() - t0
        validate_s = None
        if args.validate:
            from sherman_tpu.models.validate import check_structure_device
            t0 = time.time()
            info = check_structure_device(t2)
            validate_s = time.time() - t0
            assert info["keys"] == args.keys
    finally:
        if args.dir is None:
            for f in (path, dpath):
                try:
                    os.unlink(f)
                except OSError:
                    pass
            try:
                os.rmdir(td)
            except OSError:
                pass

    if delta:
        print("# {:>10s} {:>12s} {:>12s}".format("", "full", "delta"),
              file=sys.stderr)
        print("# {:>10s} {:>12.2f} {:>12.2f}".format(
            "save (s)", ckpt_s, delta["checkpoint_s"]), file=sys.stderr)
        print("# {:>10s} {:>12.3f} {:>12.3f}".format(
            "size (GB)", size / 1e9, delta["npz_bytes"] / 1e9),
            file=sys.stderr, flush=True)

    jab = None
    if args.journal_ab_threads > 0:
        jab = journal_group_commit_ab(threads=args.journal_ab_threads,
                                      appends=args.journal_ab_appends)
        print("# journal group-commit A/B ({} writers x {} records):"
              .format(args.journal_ab_threads, args.journal_ab_appends),
              file=sys.stderr)
        print("# {:>10s} {:>9s} {:>12s} {:>11s} {:>12s} {:>14s}".format(
            "mode", "acks/s", "ack_mean_ms", "ack_p99_ms",
            "added_ack_ms", "acks_per_fsync"), file=sys.stderr)
        for label, r in jab.items():
            print("# {:>10s} {:>9.0f} {:>12.3f} {:>11.3f} {:>12.3f} "
                  "{:>14.2f}".format(label, r["acks_per_s"],
                                     r["ack_mean_ms"], r["ack_p99_ms"],
                                     r["added_ack_ms"],
                                     r["acks_per_fsync"]),
                  file=sys.stderr, flush=True)
        g2 = jab.get("gc_2ms")
        if g2 is not None and args.journal_ab_threads >= 2:
            # the round-8 acceptance pin: bounded-delay group commit
            # must actually coalesce under a multi-writer load
            assert g2["acks_per_fsync"] >= 2.0, \
                f"group commit failed to coalesce: {g2}"

    print(json.dumps({
        "metric": "checkpoint_restore_at_scale",
        "value": round(ckpt_s + restore_s, 1),
        "unit": "s",
        "keys": args.keys,
        "pool_pages": pages,
        "npz_bytes": size,
        "bulk_load_s": round(build_s, 1),
        "checkpoint_s": round(ckpt_s, 1),
        "restore_s": round(restore_s, 1),
        "verify_sample": int(probe.shape[0]),
        "verify_s": round(verify_s, 1),
        "validate_s": round(validate_s, 1) if validate_s else None,
        "delta": delta,
        # per-op-fsync vs bounded-delay group commit (acks/s, ack
        # latency, coalescing ratio); RPO 0 in every mode — the drill
        # pins it with the knob ON
        "journal_group_commit": jab,
    }))


if __name__ == "__main__":
    main()
