#!/usr/bin/env python
"""Open-loop serving-front-door bench (``bench.py --serve``).

Where ``bench.py`` is a CLOSED loop that owns the machine, this driver
is the front door's proof of service: paced multi-tenant clients submit
independent requests through :class:`sherman_tpu.serve.ShermanServer`,
and the receipt shows the SLO-adaptive width controller settling on a
step width whose MEASURED end-to-end p99 meets the configured target
while throughput stays within 1.3x of the best fixed-width closed-loop
number at that width (measured in-process by the calibration sweep —
same tree, same programs, same host).

Methodology:

- admissions are paced by the shared ``perf_counter_ns`` sleep+spin
  pacer (``tools/common.py`` :class:`~common.AdmissionPacer`, one copy
  with ``latency_bench``); every paced tenant's jitter lands in ONE
  merged ``adm_*`` receipt with the ``adm_feasible`` verdict — a run
  whose pacing error rivals its request period was not actually offered
  at the stated rate, and says so in the JSON;
- the offered rate is ``rho x`` the calibrated closed-loop throughput
  of the width the controller would pick under saturation (open loops
  offered exactly the service rate are marginally stable — the
  latency_bench lesson);
- an optional GREEDY tenant submits unpaced bursts beside the polite
  tenants: its typed :class:`~sherman_tpu.serve.ServeOverloadError`
  rejects and the per-tenant served shares are the fair-share receipt;
- the serving loop runs SEALED (warmup compiles every ladder rung);
  ``retraces`` in the receipt must be 0 — the PR 8 contract applied to
  a real request path;
- writes are journaled by construction (ack gate = fsync): the
  ``journal`` block carries this run's acks-per-fsync coalescing.

``--crash-drill`` instead runs the durability drill: concurrent writer
tenants stream value re-stamps through the front door while a
client-side ledger records every ACKED (key, value); the server is
KILLED mid-traffic (journal left unclosed, exactly what a crash leaves
behind), the base image is rebuilt, the journal replays, and the
receipt pins ``rpo_ops == 0`` — no acked write lost — plus
``acks_per_fsync > 1`` under concurrent writers with group commit on.

Run::

    python tools/serve_bench.py [--keys 200000] [--secs 6]
        [--widths 1024,4096,16384] [--p99-ms 0 (auto)] [--tenants 3]
        [--req-ops 512] [--rho 0.8] [--write-frac 0.1] [--no-greedy]
        [--cache] [--crash-drill]

Prints ONE JSON line (``metric: serve_bench`` / ``serve_crash_drill``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from common import AdmissionPacer, pages_for_keys, setup_platform  # noqa: E402

STAMP0 = 0xD00D          # bulk-load value stamp (key ^ STAMP0)
STAMP1 = 0x5EED_0001     # open-loop write re-stamp


def build_engine(n_keys: int, widths, cache: bool):
    """Cluster + bulk-loaded tree + engine (+ router, + optional
    sketch-admission leaf cache) — the drivers' shared prologue."""
    from sherman_tpu.cluster import Cluster
    from sherman_tpu.config import DSMConfig, TreeConfig
    from sherman_tpu.models import batched
    from sherman_tpu.models.btree import Tree
    from sherman_tpu import native

    if native.available():
        salt = 0x5E17_AB1E_5A17
        while True:
            try:
                keys, rank_to_key = native.synthetic_keyspace(n_keys, salt)
                break
            except ValueError:
                salt += 1
    else:
        rng = np.random.default_rng(7)
        keys = np.unique(rng.integers(
            1, (1 << 63), int(n_keys * 1.05), dtype=np.uint64))[:n_keys]
        rank_to_key = np.sort(keys)
    vals = keys ^ np.uint64(STAMP0)
    cfg = DSMConfig(machine_nr=1, pages_per_node=pages_for_keys(n_keys),
                    locks_per_node=65_536, step_capacity=max(widths),
                    chunk_pages=1024)
    cluster = Cluster(cfg)
    tree = Tree(cluster)
    batched.bulk_load(tree, keys, vals)
    # engine batch width bounds the WRITE path's padded step (the
    # ingress read path does its own per-rung padding and never uses
    # it): a write flush stalls the single dispatcher for one engine
    # op, so its width is a read-p99 tax — keep it at the mid rung,
    # not the widest
    eng_b = min(4096, max(widths))
    eng = batched.BatchedEngine(tree, batch_per_node=eng_b,
                                tcfg=TreeConfig(sibling_chase_budget=1))
    eng.attach_router()
    if cache:
        # sketch-driven admission from REAL request streams: the front
        # door's read path feeds the decayed top-K sketch, and every
        # admit_every observed batches the hottest keys are re-admitted
        eng.attach_leaf_cache(slots=4096, admit_every=16)
    return cluster, tree, eng, keys, rank_to_key


def make_sampler(n_keys: int, theta: float, rank_to_key, seed: int):
    from sherman_tpu import native
    if native.available() and theta > 0:
        zg = native.ZipfGen(n_keys, theta, seed=seed)
        return lambda n: rank_to_key[zg.sample(n)]
    from sherman_tpu.workload.zipf import ZipfGen, uniform_ranks
    rng = np.random.default_rng(seed)
    if theta > 0:
        zg = ZipfGen(n_keys, theta, seed=seed)
        return lambda n: rank_to_key[zg.sample(n)]
    return lambda n: rank_to_key[uniform_ranks(n_keys, n, rng)]


def run_serve(a) -> dict:
    from sherman_tpu import obs
    from sherman_tpu.errors import ShermanError
    from sherman_tpu.models.batched import DegradedError
    from sherman_tpu.serve import (ServeConfig, ServeOverloadError,
                                   ShermanServer)
    from sherman_tpu.utils.journal import Journal

    widths = tuple(int(w) for w in a.widths.split(","))
    t0 = time.time()
    cluster, tree, eng, keys, rank_to_key = build_engine(
        a.keys, widths, a.cache)
    print(f"# build + bulk load {time.time() - t0:.1f}s "
          f"(keys={a.keys}, cache={'on' if a.cache else 'off'})",
          file=sys.stderr)

    jdir = a.journal_dir or tempfile.mkdtemp(prefix="serve-journal-")
    jpath = os.path.join(jdir, "serve-journal.bin")
    journal = Journal(jpath, sync=True, group_commit_ms=a.group_commit_ms)
    # provisional huge target: calibration first, then re-aim (auto
    # mode picks the target FROM the measured frontier below)
    cfg = ServeConfig(widths=widths,
                      p99_targets_ms={c: (a.p99_ms or 1e9)
                                      for c in ("read", "scan",
                                                "insert", "delete")},
                      fusion=a.fusion,
                      group_commit_ms=a.group_commit_ms,
                      # one engine chunk per write flush: a wider
                      # flush is a longer dispatcher stall every read
                      # behind it pays
                      write_width=2048,
                      # end-to-end p99 model on a GIL'd CPU host:
                      # formation wait (~wall/rho) + the in-flight
                      # pipeline slot (~wall) + service (~wall) +
                      # scheduling jitter — ~3.5x the step wall, vs
                      # the library's 2x default for a co-located
                      # accelerator host
                      model_mult=3.5)
    srv = ShermanServer(eng, cfg, journal=journal)
    calib_n = min(a.keys, 4096)
    absent = np.asarray([int(keys.max()) - 1], np.uint64)
    absent = absent[~np.isin(absent, keys)]
    calib = srv.start(
        calib_keys=keys[:: max(1, a.keys // 65536)],
        calib_writes=(keys[:calib_n], keys[:calib_n] ^ np.uint64(STAMP0)),
        calib_delete_keys=absent if absent.size else None)
    for w, c in sorted(calib.items()):
        print(f"# calib W={w:>7}: {c['wall_ms']:8.2f} ms/step closed "
              f"-> {c['ops_s'] / 1e6:6.2f} M ops/s", file=sys.stderr)

    # aim the controller: explicit --p99-ms, or AUTO = a target sitting
    # between the second-widest and widest rungs' modeled p99 so the
    # adaptive pick has a real ceiling to respect (the widest rung is
    # deliberately infeasible when walls grow with width).  The 2.5x
    # slack over the idle-calibration model absorbs the wall inflation
    # a CPU mesh pays once client threads share the cores with the
    # "device" (~2x measured) — without it the mid rung sits exactly
    # on the feasibility boundary and the pick flaps.
    if a.p99_ms:
        target = float(a.p99_ms)
    else:
        w_mid = widths[-2] if len(widths) > 1 else widths[-1]
        target = cfg.model_mult * calib[w_mid]["wall_ms"] * 2.5
    srv.retarget("read", target)
    n_paced = max(1, a.tenants)

    jstats0 = journal.stats()  # calibration's appends/fsyncs excluded
    stats_lock = threading.Lock()
    cstats = {"rejects": 0, "degraded_rejects": 0, "bad_values": 0,
              "reqs": 0, "write_reqs": 0, "inflight_failures": 0}
    pacers: list[AdmissionPacer] = []
    ok_vals = (np.uint64(STAMP0), np.uint64(STAMP1))

    def check_reads(keys_req, vals_out, found):
        # every loaded key must be found, valued with either stamp
        # (writes re-stamp concurrently)
        x = vals_out ^ keys_req
        return int(np.sum(~(found & ((x == ok_vals[0])
                                     | (x == ok_vals[1])))))

    def client(tenant: str, seed: int, stop: threading.Event,
               period: float, write_frac: float):
        # requests are PRE-GENERATED (the bench's pre-staged-batches
        # idiom) and results audited AFTER the phase: on a CPU mesh the
        # clients share cores with the "device", so per-request numpy
        # work inside the paced loop would throttle the very server
        # being measured
        sample = make_sampler(a.keys, a.theta, rank_to_key, seed)
        reqpool = [np.ascontiguousarray(sample(a.req_ops), np.uint64)
                   for _ in range(128)]
        wmask = np.random.default_rng(seed).random(4096) < write_frac
        pacer = AdmissionPacer(period, spin_ms=a.spin_ms)
        with stats_lock:
            pacers.append(pacer)
        futs = []    # (future, request keys) in flight
        results = []  # (request keys, result) for the post-phase audit
        local = {"rejects": 0, "deg": 0, "bad": 0, "reqs": 0,
                 "writes": 0, "seen": 0, "failed": 0}

        def drain(f, kreq):
            try:
                res = f.result(timeout=60)
            except (ServeOverloadError, DegradedError):
                local["rejects"] += 1
                return
            except ShermanError:
                # in-flight failure (dispatch error, result timeout):
                # counted, never a silent thread death that drops this
                # tenant's stats from the receipt
                local["failed"] += 1
                return
            if f.op == "read":
                # sample 1-in-4 AT APPEND time: retaining every result
                # for a post-phase audit would hold GBs at the chip
                # parameters (65536-op requests x 30 s)
                local["seen"] += 1
                if local["seen"] % 4 == 0:
                    results.append((kreq, res))

        pacer.start()
        i = 0
        while not stop.is_set():
            pacer.wait_turn(i)
            kreq = reqpool[i & 127]
            write = bool(wmask[i & 4095])
            i += 1
            try:
                if write:
                    fut = srv.submit("insert", kreq,
                                     kreq ^ np.uint64(STAMP1),
                                     tenant=tenant)
                    local["writes"] += 1
                else:
                    fut = srv.submit("read", kreq, tenant=tenant)
                futs.append((fut, kreq))
                local["reqs"] += 1
            except ServeOverloadError:
                local["rejects"] += 1
            except DegradedError:
                local["deg"] += 1
            # reap completed futures without blocking the pacer; only
            # block (bounded in-flight) when the backlog runs away
            while futs and futs[0][0].done():
                drain(*futs.pop(0))
            while len(futs) > 256:
                drain(*futs.pop(0))
        for f, kreq in futs:
            drain(f, kreq)
        # value audit of the sampled results, off the timed phase
        for kreq, (vals_out, found) in results:
            local["bad"] += check_reads(kreq, vals_out, found)
        with stats_lock:
            cstats["rejects"] += local["rejects"]
            cstats["degraded_rejects"] += local["deg"]
            cstats["bad_values"] += local["bad"]
            cstats["reqs"] += local["reqs"]
            cstats["write_reqs"] += local["writes"]
            cstats["inflight_failures"] += local["failed"]

    def greedy(tenant: str, seed: int, stop: threading.Event):
        """Unpaced burst tenant: the fair-share test's adversary —
        admission must cap it at its share with typed rejects while
        the polite tenants keep admitting into theirs."""
        sample = make_sampler(a.keys, a.theta, rank_to_key, seed)
        reqpool = [np.ascontiguousarray(sample(a.req_ops), np.uint64)
                   for _ in range(32)]
        futs = []
        i = 0
        while not stop.is_set():
            i += 1
            try:
                futs.append(srv.submit("read", reqpool[i & 31],
                                       tenant=tenant))
            except ServeOverloadError:
                time.sleep(0.002)
            while len(futs) > 64:
                try:
                    futs.pop(0).result(timeout=60)
                except ShermanError:
                    pass
        for f in futs:
            try:
                f.result(timeout=60)
            except ShermanError:
                pass

    # -- PHASE 0 (capacity probe): ONE unpaced loader saturates the
    # front door at the controller's settled width — the measured
    # OPEN-loop capacity.  The within-1.3x pin compares THIS number to
    # the same width's closed-loop calibration: the front door's whole
    # machinery (admission, coalescing, futures, tracker) may cost at
    # most 30% of the closed loop it wraps.
    served0 = srv.served_ops
    picks0 = dict(srv.controller.picks)
    stop0 = threading.Event()
    ld = threading.Thread(target=greedy, args=("loader", 555, stop0),
                          daemon=True)
    t1 = time.perf_counter()
    ld.start()
    time.sleep(min(2.5, a.secs / 2))
    stop0.set()
    ld.join(timeout=120)
    cap_elapsed = time.perf_counter() - t1
    cap_ops_s = (srv.served_ops - served0) / cap_elapsed
    cap_picks = {w: srv.controller.picks[w] - picks0.get(w, 0)
                 for w in srv.controller.picks}
    settled = max(cap_picks.items(), key=lambda kv: kv[1])[0]
    closed_at_settled = calib[settled]["ops_s"]
    ratio = closed_at_settled / cap_ops_s if cap_ops_s else None
    print(f"# capacity: {cap_ops_s / 1e6:.2f} M ops/s open-loop "
          f"saturated at settled W={settled} (closed "
          f"{closed_at_settled / 1e6:.2f} M -> ratio {ratio:.2f})",
          file=sys.stderr)

    # -- PHASE A (SLO): paced tenants at a SUSTAINABLE offered rate —
    # the p99-vs-target receipt.  The anchor is rho x the MID rung's
    # closed rate, not the saturated capacity: step fill (and with it
    # the front door's effective service rate) is a function of queue
    # depth, so "60% of saturated capacity" is NOT automatically
    # stable at shallow queues — the paced regime serves narrower
    # steps than the flooded one.  The adversarial flooder is
    # deliberately ABSENT here: a tenant that saturates the admission
    # queue by design makes every request's latency the queue-cap
    # drain time, which measures the cap, not the width.
    w_mid = widths[-2] if len(widths) > 1 else widths[-1]
    offered_ops_s = a.rho * calib[w_mid]["ops_s"]
    req_rate = offered_ops_s / a.req_ops
    period_s = n_paced / req_rate
    print(f"# target p99 {target:.2f} ms; offering "
          f"{offered_ops_s / 1e6:.2f} M ops/s ({req_rate:.0f} req/s x "
          f"{a.req_ops} ops, rho {a.rho}, {n_paced} paced tenants)",
          file=sys.stderr)
    srv.tracker.reset()
    served0 = srv.served_ops
    stopA = threading.Event()
    threads = [threading.Thread(
        target=client,
        args=(f"tenant{k}", 100 + k, stopA, period_s, a.write_frac),
        daemon=True) for k in range(n_paced)]
    t1 = time.perf_counter()
    for t in threads:
        t.start()
    time.sleep(a.secs)
    stopA.set()
    for t in threads:
        t.join(timeout=120)
    elapsed = time.perf_counter() - t1
    window = srv.tracker.window()
    served_ops = srv.served_ops - served0
    width_picks = dict(srv.controller.picks)
    downshifts = srv.controller.downshifts
    slo_picks = {w: width_picks[w] - cap_picks.get(w, 0)
                 - picks0.get(w, 0) for w in width_picks}
    slo_settled = max(slo_picks.items(), key=lambda kv: kv[1])[0] \
        if any(slo_picks.values()) else settled

    # -- PHASE B (fairness): an unpaced greedy flooder beside a polite
    # paced tenant — the fair-share shares + typed-reject receipts
    fairness = None
    if a.greedy:
        stopB = threading.Event()
        tb = [threading.Thread(target=greedy, args=("greedy", 999, stopB),
                               daemon=True),
              threading.Thread(target=client,
                               args=("polite", 777, stopB,
                                     period_s * 2, 0.0),
                               daemon=True)]
        for t in tb:
            t.start()
        time.sleep(min(3.0, a.secs / 2))
        stopB.set()
        for t in tb:
            t.join(timeout=120)
        tstats = srv.stats()["tenants"]
        phase_b = {name: tstats[name] for name in ("greedy", "polite")
                   if name in tstats}
        b_served = max(1, sum(t["served_ops"] for t in phase_b.values()))
        for t in phase_b.values():
            t["share"] = round(t["served_ops"] / b_served, 4)
        fairness = {
            "tenants": phase_b,
            "greedy_rejects": phase_b.get("greedy", {}).get(
                "rejected_overload", 0),
            "polite_rejects": phase_b.get("polite", {}).get(
                "rejected_overload", 0),
        }

    sstats = srv.stats()
    retraces = srv.retraces
    srv.stop()
    journal.close()
    serve_ops_s = served_ops / elapsed
    read_w = window.get("read") or {}
    ins_w = window.get("insert") or {}
    p99_read = read_w.get("p99_ms")
    adm = pacers[0] if pacers else AdmissionPacer(period_s)
    for p in pacers[1:]:
        adm.merge_errors(p)
    adm_receipt = adm.jitter_receipt()
    obs_slo = obs.slo_window()

    out = {
        "schema_version": 3,
        "metric": "serve_bench",
        "keys": a.keys,
        "theta": a.theta,
        "nodes": 1,
        "secs": round(elapsed, 2),
        "serve_ops_s": round(serve_ops_s),
        "serve_read_p99_ms": round(p99_read, 3) if p99_read else None,
        "serve_write_p99_ms": round(ins_w["p99_ms"], 3)
        if ins_w.get("p99_ms") else None,
        "serve": {
            "p99_targets_ms": {"read": round(target, 3)},
            "p99_target_met": bool(p99_read is not None
                                   and p99_read <= target),
            "widths": list(widths),
            # width the saturated capacity phase settled on (the
            # throughput pin's width) and the SLO phase's own settle —
            # step fill follows queue depth, so they may differ
            "settled_width": settled,
            "slo_settled_width": slo_settled,
            "width_picks": width_picks,
            "slo_picks": slo_picks,
            "downshifts": downshifts,
            "fusion": a.fusion,
            "offered_ops_s": round(offered_ops_s),
            "rho": a.rho,
            "req_ops": a.req_ops,
            "requests": cstats["reqs"],
            "write_requests": cstats["write_reqs"],
            "closed_loop": {str(w): round(c["ops_s"])
                            for w, c in calib.items()},
            # capacity pin: SATURATED open-loop throughput at the
            # settled width vs the same width's closed-loop number
            "capacity_ops_s": round(cap_ops_s),
            "capacity_picks": cap_picks,
            "closed_ops_s_at_settled": round(closed_at_settled),
            "ratio_vs_closed": round(ratio, 3) if ratio else None,
            "within_1_3x": bool(ratio is not None and ratio <= 1.3),
            "tenants": {n: t for n, t in sstats["tenants"].items()
                        if n.startswith("tenant")},
            "fairness": fairness,
            "rejects": sstats["rejects"],
            "client_rejects": cstats["rejects"],
            "inflight_failures": cstats["inflight_failures"],
            "bad_values": cstats["bad_values"],
            "window": {cls: {k: round(float(v), 3)
                             for k, v in st.items()}
                       for cls, st in window.items()},
            "slo_window": {cls: {k: round(float(v), 3)
                                 for k, v in st.items()}
                           for cls, st in obs_slo.items()},
            "sealed": sstats["sealed"],
            "retraces": retraces,
            # traffic-phase journal coalescing (calibration excluded):
            # acked write REQUESTS per real fsync
            "journal": {
                "appends": sstats["journal"]["appends"]
                - jstats0["appends"],
                "fsyncs": sstats["journal"]["fsyncs"]
                - jstats0["fsyncs"],
                "acked_write_requests": cstats["write_reqs"],
                "acks_per_fsync": round(
                    cstats["write_reqs"]
                    / (sstats["journal"]["fsyncs"] - jstats0["fsyncs"]),
                    2)
                if sstats["journal"]["fsyncs"] > jstats0["fsyncs"]
                else None,
            } if sstats.get("journal") else None,
            "cache": sstats.get("cache"),
            **adm_receipt,
        },
    }
    ok = (retraces == 0 and cstats["bad_values"] == 0
          and out["serve"]["p99_target_met"]
          and out["serve"]["within_1_3x"])
    if fairness is not None:
        ok = ok and fairness["greedy_rejects"] > 0 \
            and fairness["polite_rejects"] == 0
    out["ok"] = bool(ok)
    print(f"# serve: {served_ops} ops in {elapsed:.2f}s -> "
          f"{serve_ops_s / 1e6:.2f} M ops/s open-loop; read p99 "
          f"{p99_read if p99_read else float('nan'):.2f} ms vs target "
          f"{target:.2f} ({'MET' if out['serve']['p99_target_met'] else 'MISSED'}); "
          f"settled W={settled} (closed {closed_at_settled / 1e6:.2f} M, "
          f"ratio {ratio:.2f}); retraces {retraces}; "
          f"rejects {sstats['rejects']}; "
          f"adm p99 {adm_receipt['adm_jitter_p99_ms']:.3f} ms "
          f"({'feasible' if adm_receipt['adm_feasible'] else 'NOT FEASIBLE'})",
          file=sys.stderr)
    return out


def run_crash_drill(a) -> dict:
    """Journaled-ack durability drill — see the module docstring."""
    from sherman_tpu.models import batched
    from sherman_tpu.models.btree import Tree
    from sherman_tpu.cluster import Cluster
    from sherman_tpu.config import DSMConfig, TreeConfig
    from sherman_tpu.serve import ServeConfig, ShermanServer
    from sherman_tpu.utils import journal as J
    from sherman_tpu.errors import StateError

    widths = tuple(int(w) for w in a.widths.split(","))
    cluster, tree, eng, keys, rank_to_key = build_engine(
        a.keys, widths, False)
    jdir = a.journal_dir or tempfile.mkdtemp(prefix="serve-crash-")
    jpath = os.path.join(jdir, "serve-journal.bin")
    journal = J.Journal(jpath, sync=True,
                        group_commit_ms=a.group_commit_ms)
    cfg = ServeConfig(widths=widths,
                      p99_targets_ms={c: 1e9 for c in
                                      ("read", "scan", "insert",
                                       "delete")},
                      group_commit_ms=a.group_commit_ms,
                      write_linger_ms=0.5)
    srv = ShermanServer(eng, cfg, journal=journal)
    srv.start(calib_keys=keys[:4096],
              calib_writes=(keys[:512], keys[:512] ^ np.uint64(STAMP0)))
    jstats0 = journal.stats()  # calibration fsyncs excluded (run_serve
    # does the same): the acks/fsync pin must count traffic only

    n_writers = 4
    per = a.keys // (n_writers + 1)
    acked: list[dict] = [dict() for _ in range(n_writers)]
    stop = threading.Event()

    def writer(w: int):
        # DISJOINT key slice per writer: per-key FIFO within one tenant
        # makes "last acked value" well-defined for the RPO audit
        my = keys[w * per:(w + 1) * per]
        rng = np.random.default_rng(w)
        gen = 0
        while not stop.is_set():
            gen += 1
            idx = rng.integers(0, my.size, 128)
            kreq = np.unique(my[idx])
            vreq = kreq ^ np.uint64(STAMP1) ^ np.uint64(gen)
            try:
                fut = srv.submit("insert", kreq, vreq,
                                 tenant=f"writer{w}")
                ok = fut.result(timeout=30)
            except StateError:
                return  # the crash: in-flight op never acked, not owed
            except Exception:
                continue
            # the ack gate passed: the OK rows are DURABLE by contract
            # (a lock-timeout row is typed-rejected, never journaled —
            # the ledger must not hold the engine to a write it
            # refused)
            for k, v, o in zip(kreq.tolist(), vreq.tolist(),
                               ok.tolist()):
                if o:
                    acked[w][k] = v

    threads = [threading.Thread(target=writer, args=(w,), daemon=True)
               for w in range(n_writers)]
    for t in threads:
        t.start()
    time.sleep(a.secs)
    # CRASH: kill mid-traffic — no drain, journal left unclosed
    srv.kill()
    stop.set()
    for t in threads:
        t.join(timeout=60)
    jstats = journal.stats()
    n_acked = sum(len(d) for d in acked)
    acked_reqs = srv.acked_writes
    fsyncs = jstats["fsyncs"] - jstats0["fsyncs"]
    acks_per_fsync = acked_reqs / fsyncs if fsyncs else None

    # RECOVERY: rebuild the base image (the bulk-loaded state the
    # journal's records apply onto), replay, audit every acked write
    cfg2 = DSMConfig(machine_nr=1,
                     pages_per_node=pages_for_keys(a.keys),
                     locks_per_node=65_536, step_capacity=max(widths),
                     chunk_pages=1024)
    tree2 = Tree(Cluster(cfg2))
    batched.bulk_load(tree2, keys, keys ^ np.uint64(STAMP0))
    eng2 = batched.BatchedEngine(tree2, batch_per_node=max(widths),
                                 tcfg=TreeConfig(sibling_chase_budget=1))
    eng2.attach_router()
    replay_stats = J.replay(jpath, eng2)
    missing = 0
    for d in acked:
        if not d:
            continue
        ak = np.fromiter(d.keys(), np.uint64, len(d))
        av = np.fromiter(d.values(), np.uint64, len(d))
        got, found = eng2.search(ak)
        missing += int(np.sum(~(found & (got == av))))
    out = {
        "schema_version": 3,
        "metric": "serve_crash_drill",
        "keys": a.keys,
        "acked_write_requests": acked_reqs,
        "acked_rows": n_acked,
        "rpo_ops": missing,
        "group_commit_ms": a.group_commit_ms,
        "journal": jstats,
        "acks_per_fsync": round(acks_per_fsync, 2)
        if acks_per_fsync else None,
        "replay": replay_stats,
        "ok": bool(missing == 0 and n_acked > 0
                   and (acks_per_fsync or 0) > 1),
    }
    print(f"# crash drill: {acked_reqs} acked write reqs ({n_acked} "
          f"rows) across {n_writers} concurrent writers; "
          f"{fsyncs} fsyncs -> {acks_per_fsync:.1f} "
          f"acks/fsync; replayed {replay_stats['records']} records; "
          f"RPO {missing} ops", file=sys.stderr)
    return out


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(
        description="open-loop serving front-door bench / crash drill")
    ap.add_argument("--keys", type=int, default=200_000)
    ap.add_argument("--theta", type=float, default=0.99)
    ap.add_argument("--widths", type=str, default="1024,4096,16384")
    ap.add_argument("--p99-ms", type=float, default=0.0,
                    help="read p99 target in ms (0 = auto from the "
                         "calibrated frontier)")
    ap.add_argument("--secs", type=float, default=6.0)
    ap.add_argument("--tenants", type=int, default=3)
    ap.add_argument("--req-ops", type=int, default=1024,
                    help="ops per client request (one RPC's batch)")
    ap.add_argument("--rho", type=float, default=0.6,
                    help="SLO-phase offered fraction of the MID "
                         "rung's closed-loop calibration rate (the "
                         "sustainable paced anchor).  The "
                         "throughput-vs-closed pin is the capacity "
                         "phase's; this phase must be genuinely "
                         "stable for its p99 to measure the width, "
                         "not a standing queue")
    ap.add_argument("--write-frac", type=float, default=0.0,
                    help="write fraction of SLO-phase requests "
                         "(default 0: the SLO phase measures the "
                         "headline read class, YCSB-C).  Every write "
                         "flush blocks the single dispatcher for one "
                         "engine op (~the insert wall — the journaled "
                         "single-writer contract), so any nonzero "
                         "fraction taxes the read p99 by that stall; "
                         "the write path's own receipts are the crash "
                         "drill's (rpo_ops, acks/fsync)")
    ap.add_argument("--spin-ms", type=float, default=2.0)
    ap.add_argument("--fusion", choices=("aligned", "pipelined"),
                    default="pipelined")
    ap.add_argument("--no-greedy", dest="greedy", action="store_false",
                    help="drop the unpaced burst tenant")
    ap.add_argument("--cache", action="store_true",
                    help="attach the hot-key leaf cache with "
                         "sketch-driven admission (admit_every=16)")
    ap.add_argument("--group-commit-ms", type=float, default=2.0)
    ap.add_argument("--journal-dir", type=str, default=None)
    ap.add_argument("--crash-drill", action="store_true")
    a = ap.parse_args(argv)

    jax = setup_platform(1)
    jax.config.update("jax_compilation_cache_dir", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    out = run_crash_drill(a) if a.crash_drill else run_serve(a)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
