#!/usr/bin/env python
"""Client-contract drill: exactly-once + deadlines + linearizability
across chaos, a cold crash, recovery, and a migration.

The fourth end-to-end rehearsal (chaos drill = detection, recovery
drill = durability, reshard drill = capacity) — this one pins the
CLIENT-VISIBLE contract of the serving front door:

  phase 1  build + bulk-load an N-node CPU mesh, arm the recovery
           plane (base checkpoint + v2 journal with request ids),
           start the front door with the exactly-once dedup window,
           deadlines, and the inline sampling auditor attached
           (``sherman_tpu/audit.py``), SEALED after calibration.
  phase A  open-loop clients (``serve.RetryingClient``: capped
           exponential backoff + jitter, read hedging after p99,
           writes retried only under request ids) hammer reads +
           exactly-once inserts through a chaos storm (wedged locks +
           dropped CAS winners — the absorbable storm; every fault is
           revoked/retried, never a wrong answer), with a delta
           checkpoint mid-stream (journal rotation must CARRY the ack
           window forward) and a deadline burst (tiny budgets under
           load; every shed request must fail TYPED).  Every client
           records its full (key, op, invoke, respond) history.
           The zero-retrace pin holds here: dedup + deadlines +
           auditor sampling on, sealed loop, ``retraces == 0``.
  crash    the server is KILLED mid-traffic (no drain, journal left
           unclosed) and the journal tail is TORN (half a record).
  recover  ``RecoveryPlane.recover``: restore + replay reconstructs
           both the STATE (rpo_ops == 0) and the exactly-once WINDOW
           (J_ACK records -> ``plane.dedup_window``), adopted by a
           fresh front door via ``seed_dedup``.
  retry    the drill's teeth: for sampled pre-crash request ids, the
           keys are first re-written to NEW values (fresh rids), then
           the OLD rids are retried with their ORIGINAL payloads — a
           correct plane re-acks the original result from the window
           (``fut.deduped``) and the state keeps the NEW values;
           every old payload found in state afterwards counts a
           ``duplicate_ack`` (pinned == 0: the lost-update bug the
           window kills).
  migrate  a live migration to M nodes runs under fresh traffic,
           completes, and the quiesced cutover image is restored; the
           final state must serve EVERY acked write (lost_acks == 0).
  audit    the combined client-side history (deduped re-acks excluded
           — they are the original acks, not new writes) is checked
           offline per key: ``linearizable == true``; the receipt also
           carries the inline auditor's verdict and its self-timed
           cost fraction (< 2% of the serve wall — the obs-cost pin).

Runs on the CPU mesh anywhere (``bench.py --contract-drill`` forwards
here; ``scripts/contract_ci.sh`` pins it in CI).  Prints ONE JSON line
``{"metric": "contract_drill", "ok": true, "duplicate_acks": 0,
"lost_acks": 0, "rpo_ops": 0, "linearizable": true, ...}`` and mirrors
it to ``SHERMAN_CONTRACT_RECEIPT`` when set.  perfgate treats the
committed receipt as a robustness artifact: never throughput-gated,
but ``duplicate_acks > 0`` / ``lost_acks > 0`` / ``linearizable ==
false`` is a hard red.  Env knobs: SHERMAN_DRILL_KEYS (default 4000),
SHERMAN_DRILL_NODES (default 2), SHERMAN_DRILL_TARGET_NODES (default
3), SHERMAN_CHAOS_SEED, SHERMAN_DRILL_SECS (phase-A seconds).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

from common import build_cluster, pages_for_keys, setup_platform

SALT = 0xC0117AC7  # bulk-load value stamp (key ^ SALT)


def main(argv=None) -> dict:
    p = argparse.ArgumentParser()
    p.add_argument("--keys", type=int,
                   default=int(os.environ.get("SHERMAN_DRILL_KEYS", 4000)))
    p.add_argument("--nodes", type=int,
                   default=int(os.environ.get("SHERMAN_DRILL_NODES", 2)))
    p.add_argument("--target-nodes", type=int,
                   default=int(os.environ.get("SHERMAN_DRILL_TARGET_NODES",
                                              3)))
    p.add_argument("--seed", type=int,
                   default=int(os.environ.get("SHERMAN_CHAOS_SEED", 7)))
    p.add_argument("--secs", type=float,
                   default=float(os.environ.get("SHERMAN_DRILL_SECS", 3.0)))
    p.add_argument("--dir", default=None,
                   help="drill directory (default: a tempdir)")
    a = p.parse_args(argv)
    setup_platform(max(a.nodes, a.target_nodes))

    from sherman_tpu import audit as A
    from sherman_tpu import chaos as CH
    from sherman_tpu import obs
    from sherman_tpu.config import TreeConfig
    from sherman_tpu.errors import ShermanError
    from sherman_tpu.migrate import Migrator
    from sherman_tpu.models import batched
    from sherman_tpu.models.batched import DegradedError
    from sherman_tpu.models.btree import Tree
    from sherman_tpu.models.validate import check_structure_device
    from sherman_tpu.recovery import RecoveryPlane
    from sherman_tpu.serve import (DeadlineExceededError, RetryingClient,
                                   RetryPolicy, ServeConfig, ShermanServer)
    from sherman_tpu.utils import checkpoint as CK
    from sherman_tpu.utils import journal as J

    t_start = time.time()
    out: dict = {"metric": "contract_drill", "seed": a.seed, "ok": False,
                 "nodes": a.nodes, "target_nodes": a.target_nodes}
    root = a.dir or tempfile.mkdtemp(prefix="sherman_contract_")
    rdir = os.path.join(root, "recovery")
    mdir = os.path.join(root, "migration")
    out["dir"] = root

    # -- phase 1: build + recovery plane + audited front door -----------------
    ppn = pages_for_keys(a.keys)
    cluster, tree, eng = build_cluster(
        a.nodes, ppn, batch_per_node=512,
        locks_per_node=1024, chunk_pages=64)
    rng = np.random.default_rng(3)
    keys = np.unique(rng.integers(1, 1 << 56, int(a.keys * 1.05),
                                  dtype=np.uint64))[:a.keys]
    vals = keys ^ np.uint64(SALT)
    batched.bulk_load(tree, keys, vals)
    eng.attach_router()
    check_structure_device(tree)
    plane = RecoveryPlane(cluster, tree, eng, rdir, group_commit_ms=2.0)
    plane.checkpoint_base()

    widths = (256 * a.nodes, 1024 * a.nodes)
    big = {c: 1e9 for c in ("read", "scan", "insert", "delete")}

    def front_door(engine, auditor=None):
        cfg = ServeConfig(widths=widths, p99_targets_ms=dict(big),
                          write_linger_ms=0.5, write_width=2048,
                          group_commit_ms=2.0)
        srv = ShermanServer(engine, cfg, auditor=auditor)
        absent = np.asarray([1 << 60], np.uint64)
        # VALUE-PRESERVING calibration writes: re-stamp the keys with
        # their CURRENT values (a recovered engine's state already
        # carries acked post-bulk writes — re-stamping bulk values
        # here would be a silent lost update the final audit flags)
        ck = keys[:256]
        cv, cf = engine.search(ck)
        srv.start(calib_keys=keys,
                  calib_writes=(ck[cf], np.asarray(cv)[cf]),
                  calib_delete_keys=absent)
        return srv

    aud = A.Auditor(sample_mod=4, interval_s=0.1)
    aud.seed_initial(keys, vals)
    srv = front_door(eng, auditor=aud)
    snap0 = obs.snapshot()

    # client-side ledgers (merged post-phase): the acked-op ledger per
    # writer slice, the per-rid record for the retry-across-crash leg,
    # and the full client-observed history for the offline audit
    n_writers, n_readers = 2, 2
    per = a.keys // (n_writers + 1)
    acked: list[dict] = [dict() for _ in range(n_writers)]
    # submitted-but-unacked writes (in-flight at the crash, result
    # unknown): their values feed the offline check's open_writes set —
    # a read that observed one is the legal at-least-once window, not
    # a violation
    unacked: list[dict] = [dict() for _ in range(n_writers)]
    rid_ledger: list[dict] = [dict() for _ in range(n_writers)]
    events: list[list] = [[] for _ in range(n_writers + n_readers)]
    cstats = {"read_reqs": 0, "write_reqs": 0, "rejects": 0,
              "hedges": 0, "retries": 0, "inflight_failures": 0}
    stats_lock = threading.Lock()
    stop = threading.Event()

    def writer(w: int):
        my = keys[w * per:(w + 1) * per]
        cl = RetryingClient(srv, tenant=f"writer{w}",
                            policy=RetryPolicy(max_attempts=6),
                            seed=100 + w)
        ev = events[w]
        wrng = np.random.default_rng(w)
        gen = 0
        while not stop.is_set():
            gen += 1
            kreq = np.unique(my[wrng.integers(0, my.size, 96)])
            vreq = kreq ^ np.uint64(SALT) ^ np.uint64(gen << 8)
            rid = cl.next_rid()
            t_inv = time.perf_counter()
            try:
                ok = cl.insert(kreq, vreq, rid=rid)
            except ShermanError:
                # unacked: not owed, not recorded as a write — but it
                # MAY have applied (in flight at the crash), so its
                # values stay legal for concurrent readers
                for k, v in zip(kreq.tolist(), vreq.tolist()):
                    unacked[w].setdefault(k, []).append((True, v))
                continue
            t_resp = time.perf_counter()
            rid_ledger[w][rid] = (kreq, vreq, np.array(ok))
            for k, v, o in zip(kreq.tolist(), vreq.tolist(),
                               ok.tolist()):
                if o:
                    acked[w][k] = v
                    ev.append((k, A.OP_INSERT, t_inv, t_resp, v, True))
        with stats_lock:
            cstats["write_reqs"] += len(rid_ledger[w])
            cstats["retries"] += cl.retries
            cstats["rejects"] += cl.rejects

    def reader(r: int):
        cl = RetryingClient(srv, tenant=f"reader{r}",
                            policy=RetryPolicy(max_attempts=4),
                            seed=200 + r, deadline_ms=5000.0)
        ev = events[n_writers + r]
        rrng = np.random.default_rng(50 + r)
        local_fail = 0
        while not stop.is_set():
            kreq = np.unique(keys[rrng.integers(0, keys.size, 64)])
            t_inv = time.perf_counter()
            try:
                got, found = cl.read(kreq)
            except ShermanError:
                local_fail += 1
                continue
            t_resp = time.perf_counter()
            for k, g, f in zip(kreq.tolist(), got.tolist(),
                               found.tolist()):
                ev.append((k, A.OP_READ, t_inv, t_resp,
                           g if f else None, bool(f)))
            time.sleep(0.001)
        with stats_lock:
            cstats["read_reqs"] += cl.retries + 1
            cstats["hedges"] += cl.hedges
            cstats["inflight_failures"] += local_fail

    threads = [threading.Thread(target=writer, args=(w,), daemon=True)
               for w in range(n_writers)] + \
              [threading.Thread(target=reader, args=(r,), daemon=True)
               for r in range(n_readers)]
    tA = time.perf_counter()
    for t in threads:
        t.start()

    # clean-window zero-retrace pin: dedup + deadlines + auditor
    # sampling on, sealed loop, NO storm yet — the contract plane
    # itself must not compile anything in steady state.  (The storm
    # below legitimately compiles the lock-recovery rescue path on its
    # first wedge — counted separately as rescue_retraces.)
    time.sleep(a.secs / 3)
    retraces_clean = srv.retraces
    assert retraces_clean == 0, \
        f"sealed serving loop retraced {retraces_clean}x with the " \
        "contract plane on (clean window)"

    # chaos storm mid-traffic: the ABSORBABLE kinds under live clients
    # (wedged locks revoke through the lease table, dropped CAS winners
    # retry through the bounded budget); page-corruption kinds belong
    # to the scrub/repair drills — injecting them under an audited
    # read stream would be testing detection, not the client contract
    plan = CH.FaultPlan.random(a.seed, n_faults=4, step_hi=6,
                               kinds=("wedge_lock", "drop_cas"))
    cluster.dsm.install_chaos(plan)
    # the chaos hook fires at the DSM host-step boundary, which the
    # ingress fan-out path never crosses — drive the due steps so the
    # wedges land while the client storm is live
    for _ in range(8):
        cluster.dsm.read_word(0, 0)
        time.sleep(a.secs / 24)
    time.sleep(a.secs / 3)
    cluster.dsm.install_chaos(None)
    out["chaos"] = {"faults_fired": plan.injected,
                    "plan": plan.describe()}
    assert plan.injected > 0, "chaos storm never fired"

    # delta checkpoint mid-stream: the rotation must CARRY the ack
    # window into the fresh segment (acks before this point stay
    # replayable after the crash)
    d1 = plane.checkpoint_delta()
    out["delta1"] = {"pages": int(d1["pages"])}

    # deadline burst: tiny budgets under live load — every shed
    # request must fail TYPED, never be served late or hang
    shed_typed = shed_other = served_in_time = 0
    for i in range(60):
        t0 = time.perf_counter()
        try:
            fut = srv.submit("read", keys[(i * 61) % a.keys::997],
                             tenant="deadline", deadline_ms=0.01)
            fut.result(timeout=30)
            served_in_time += 1
            assert time.perf_counter() - t0 < 30.0
        except DeadlineExceededError:
            shed_typed += 1
        except ShermanError:
            shed_other += 1  # overload reject: typed too, but not shed
    time.sleep(a.secs / 3)
    stop.set()
    for t in threads:
        t.join(timeout=120)
    wallA = time.perf_counter() - tA
    retraces = srv.retraces
    audit_cost_frac = aud.cost_frac(wallA)
    out["deadline"] = {"shed_typed": shed_typed,
                       "served_in_time": served_in_time,
                       "other_typed": shed_other,
                       "server_shed": srv.deadline_shed}
    assert shed_typed + served_in_time + shed_other == 60
    assert shed_typed > 0, "10us budgets under load never shed"
    out["phase_a"] = {"secs": round(wallA, 2),
                      "write_reqs": cstats["write_reqs"],
                      "retries": cstats["retries"],
                      "hedges": cstats["hedges"],
                      "rejects": cstats["rejects"],
                      "inflight_failures": cstats["inflight_failures"],
                      "retraces_clean_window": retraces_clean,
                      # first-use compiles of the lock-recovery rescue
                      # + checkpoint paths under the storm (not the
                      # serving loop's steady state)
                      "rescue_retraces": retraces - retraces_clean,
                      "audit_cost_frac": round(audit_cost_frac, 5)}
    assert cstats["write_reqs"] > 0 and sum(len(d) for d in acked) > 0

    # -- crash: kill the server mid-ack-stream, tear the journal tail ---------
    live_rids = {w: dict(rid_ledger[w]) for w in range(n_writers)}
    srv.kill()
    inline_verdict = aud.stats()
    jpath = eng.journal.path
    plane.close()
    with open(jpath, "ab") as f:  # crash mid-append: torn half-record
        rec = J.encode_record(J.J_UPSERT, np.asarray([1 << 40], np.uint64),
                              np.asarray([7], np.uint64), rid=0xDEAD)
        f.write(rec[: len(rec) // 2])
    del cluster, tree, eng, srv

    # -- recover: state AND the exactly-once window ---------------------------
    t0 = time.perf_counter()
    plane, cluster, tree, eng, rec = RecoveryPlane.recover(
        rdir, batch_per_node=512,
        tcfg=TreeConfig(sibling_chase_budget=1), group_commit_ms=2.0)
    out["recover"] = {"total_ms": rec["total_ms"],
                      "replayed": rec["replay"]["records"],
                      "replayed_acks": rec["replay"]["acks"],
                      "window": len(plane.dedup_window)}
    assert rec["replay"]["acks"] > 0 and plane.dedup_window, \
        "recovery reconstructed no exactly-once window"

    # RPO audit: every acked write's effect present after replay
    merged_acked: dict = {}
    for d in acked:
        merged_acked.update(d)
    ak = np.asarray(sorted(merged_acked), np.uint64)
    av = np.asarray([merged_acked[int(k)] for k in ak], np.uint64)
    got, found = eng.search(ak)
    rpo = int((~found).sum()) + int((got[found] != av[found]).sum())
    out["rpo_ops"] = rpo
    assert rpo == 0, f"RPO violated: {rpo} acked ops lost"
    out["rto_ms"] = round((time.perf_counter() - t0) * 1e3, 1)

    # -- retry across the crash: re-ack, never re-apply -----------------------
    aud2 = A.Auditor(sample_mod=4, interval_s=0.1)
    srv2 = front_door(eng, auditor=aud2)
    adopted = srv2.seed_dedup(plane.dedup_window)
    out["dedup"] = {"adopted": adopted}
    duplicate_acks = 0
    retried = 0
    post_events: list = []
    for w in range(n_writers):
        sample = list(live_rids[w].items())[-4:]
        for rid, (kreq, vreq, ok0) in sample:
            if not ok0.any():
                continue
            retried += 1
            # 1) move the keys PAST the old write (fresh rid, new value)
            vnew = kreq ^ np.uint64(SALT) ^ np.uint64(0x7777_0000)
            t_inv = time.perf_counter()
            ok2 = srv2.submit("insert", kreq, vnew, tenant=f"writer{w}",
                              rid=(0x7777 << 32) | (rid & 0xFFFFFFFF)
                              ).result(timeout=60)
            t_resp = time.perf_counter()
            for k, v, o in zip(kreq.tolist(), vnew.tolist(),
                               ok2.tolist()):
                if o:
                    merged_acked[k] = v
                    post_events.append((k, A.OP_INSERT, t_inv, t_resp,
                                        v, True))
            # 2) retry the PRE-CRASH rid with its original payload: the
            # window must re-ack the ORIGINAL result, not re-apply
            fut = srv2.submit("insert", kreq, vreq, tenant=f"writer{w}",
                              rid=rid)
            okr = fut.result(timeout=60)
            if not fut.deduped or not np.array_equal(okr, ok0):
                duplicate_acks += 1
                continue
            got, found = srv2.submit("read", kreq).result(timeout=60)
            stomped = int(np.sum(found & ok2 & (got == vreq)
                                 & (vreq != vnew)))
            if stomped:
                duplicate_acks += 1
    out["retry_across_crash"] = {"retried": retried,
                                 "dedup_hits": srv2.dedup_hits}
    out["duplicate_acks"] = duplicate_acks
    assert retried > 0, "drill retried nothing across the crash"
    assert duplicate_acks == 0, \
        f"{duplicate_acks} retried writes re-applied (lost updates)"

    # -- migration under traffic, then the final lost-acks audit --------------
    mig = Migrator(cluster, tree, eng, a.target_nodes, mdir,
                   target_pages_per_node=ppn, batch_pages=64)
    mig.start()
    mrounds = 0
    gen = 0x5109
    wrng = np.random.default_rng(99)
    while not mig.copied_all and mrounds < 10_000:
        mig.step()
        mrounds += 1
        if mrounds % 4 == 0:
            kreq = np.unique(keys[wrng.integers(0, per, 48)])
            vreq = kreq ^ np.uint64(SALT) ^ np.uint64(gen + mrounds)
            t_inv = time.perf_counter()
            try:
                ok = srv2.submit("insert", kreq, vreq, tenant="mig",
                                 rid=(0x3333 << 32) | mrounds
                                 ).result(timeout=60)
            except (ShermanError, DegradedError):
                continue
            t_resp = time.perf_counter()
            for k, v, o in zip(kreq.tolist(), vreq.tolist(),
                               ok.tolist()):
                if o:
                    merged_acked[k] = v
                    post_events.append((k, A.OP_INSERT, t_inv, t_resp,
                                        v, True))
            got, found = srv2.submit("read", kreq, tenant="mig"
                                     ).result(timeout=60)
            t2 = time.perf_counter()
            for k, g, f in zip(kreq.tolist(), got.tolist(),
                               found.tolist()):
                post_events.append((k, A.OP_READ, t_resp, t2,
                                    g if f else None, bool(f)))
    srv2.drain()
    inline2 = aud2.stats()
    dst = os.path.join(mdir, "cutover.npz")
    summary = mig.finish(dst)
    out["migration"] = {"pages_moved": int(summary["pages_moved"]),
                        "batches": int(summary["batches"]),
                        "rounds": mrounds}
    plane.close()

    # the M-node cluster serves EVERY acked write
    c3 = CK.restore(dst)
    t3 = Tree(c3)
    e3 = batched.BatchedEngine(t3, batch_per_node=512,
                               tcfg=TreeConfig(sibling_chase_budget=1))
    e3.attach_router()
    check_structure_device(t3)
    ak = np.asarray(sorted(merged_acked), np.uint64)
    av = np.asarray([merged_acked[int(k)] for k in ak], np.uint64)
    got, found = e3.search(ak)
    lost = int((~found).sum()) + int((got[found] != av[found]).sum())
    probe = keys[~np.isin(keys, ak)][:: max(1, a.keys // 512)]
    got, found = e3.search(probe)
    lost += int((~found).sum()) + int(
        (got[found] != (probe ^ np.uint64(SALT))[found]).sum())
    out["lost_acks"] = lost
    assert lost == 0, f"{lost} acked ops lost across crash + migration"

    # -- offline linearizability over the full client history -----------------
    all_events = [e for ev in events for e in ev] + post_events
    initial = {int(k): (True, int(v)) for k, v in zip(keys, vals)}
    open_w: dict = {}
    for d in unacked:
        for k, outs in d.items():
            open_w.setdefault(k, []).extend(outs)
    verdict = A.check_events(all_events, initial=initial,
                             open_writes=open_w)
    out["audit"] = {
        "events": verdict["events"],
        "keys": verdict["keys"],
        "reads_checked": verdict["reads"],
        "violations": len(verdict["violations"]),
        "linearizable": bool(verdict["linearizable"]),
        "inline_phase_a": inline_verdict,
        "inline_phase_m": inline2,
    }
    out["linearizable"] = bool(verdict["linearizable"])
    if verdict["violations"]:
        out["audit"]["first_violations"] = verdict["violations"][:3]
    assert verdict["linearizable"], \
        f"history not linearizable: {verdict['violations'][:3]}"
    assert verdict["reads"] > 0, "audit checked no reads"
    # the offline artifact + recheck (drill receipts stay re-auditable)
    jsonl = os.path.join(root, "history.jsonl")
    A.dump_jsonl(all_events, jsonl)
    re_verdict = A.check_jsonl(jsonl, initial=initial)
    assert re_verdict["events"] == verdict["events"]
    if not open_w:  # the JSONL artifact carries no open-writes side
        assert re_verdict["linearizable"]  # channel; recheck only when
        # the in-flight-at-crash set is empty
    out["history_jsonl"] = jsonl
    assert audit_cost_frac < 0.02, \
        f"inline auditor cost {audit_cost_frac:.4f} of the serve wall"

    d = obs.delta(snap0, obs.snapshot())
    out["obs"] = {k: int(d[k]) for k in sorted(d)
                  if k in ("audit.events", "audit.violations",
                           "audit.windows", "chaos.faults_injected",
                           "journal.truncated_tails", "lease.revoked",
                           "migrate.pages_moved")}
    out["elapsed_s"] = round(time.time() - t_start, 1)
    out["ok"] = True
    line = json.dumps(out)
    print(line)
    receipt = os.environ.get("SHERMAN_CONTRACT_RECEIPT")
    if receipt:
        with open(receipt, "w") as f:
            f.write(line + "\n")
    print("CONTRACT-DRILL PASS", file=sys.stderr)
    return out


if __name__ == "__main__":
    main()
