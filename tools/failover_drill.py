#!/usr/bin/env python
"""Failover drill: journal-shipped replicas, lease-epoch promotion,
and the client contract held ACROSS the failover.

The fifth end-to-end rehearsal (chaos = detection, recovery =
durability, reshard = capacity, contract = the front door) — this one
pins the REPLICATION plane (``sherman_tpu/replica.py``):

  phase 1  build + bulk-load an N-node CPU mesh, arm the recovery
           plane (base checkpoint + v2 journal), start the front door
           with exactly-once dedup, and attach a ReplicaGroup of R
           journal-shipped followers (each bootstrapped from the
           chain exactly the way ``recover()`` bootstraps, applying
           shipped records through the SAME ``apply_records`` core).
  phase A  open-loop writers (exactly-once rids) + readers hammer the
           primary while the group tails the live journal; a slice of
           reads is served by the REPLICAS through the leaf cache's
           certified probe (caught-up followers only — staleness
           forwards, never lies).  A delta checkpoint mid-stream
           retires + sweeps the shipped segment under the tail: every
           follower must re-bootstrap from the chain and converge.
           Replication lag is measured and published
           (``repl.lag_ms``).
  kill     the primary front door is KILLED mid-traffic (no drain)
           and the journal tail is TORN at the shipping boundary
           (half a frame) — in-flight, never acked.
  promote  ``group.promote``: the primary's lease EXPIRES (epoch
           bump), every follower catches up to the durable journal
           end (RPO 0 — acks gated on fsync), and the
           highest-watermark follower wins.  The dead primary then
           tries to write: the append is FENCED at the durability
           gate (typed ``StalePrimaryError``, pinned >= 1).
  resume   a fresh front door starts on the promoted engine (with its
           own new recovery plane — the new primary is itself
           recoverable), adopts the winner's replayed J_ACK window
           via ``seed_dedup``, and serves; the kill -> first-serve
           gap is the published availability gap.
  retry    pre-kill rids are retried against the NEW primary after
           the keys moved on: the window must re-ack the ORIGINAL
           result (``fut.deduped``), never re-apply —
           ``duplicate_acks == 0``.
  audit    every acked write is served by the promoted primary
           (``lost_acks == 0``, plus an untouched-key probe) and the
           merged client history (both sides of the failover) checks
           linearizable offline (``sherman_tpu/audit.py``).

Runs on the CPU mesh anywhere (``bench.py --failover-drill`` forwards
here; ``scripts/repl_ci.sh`` pins it in CI).  Prints ONE JSON line
``{"metric": "failover_drill", "ok": true, "lost_acks": 0,
"duplicate_acks": 0, "linearizable": true, ...}`` and mirrors it to
``SHERMAN_FAILOVER_RECEIPT`` when set.  perfgate treats the committed
receipt as a robustness artifact: never throughput-gated (replicated
receipts are not comparable to unreplicated rounds), but
``lost_acks > 0`` / ``duplicate_acks > 0`` / ``linearizable ==
false`` is a marginless hard red.  Env knobs: SHERMAN_DRILL_KEYS
(default 4000), SHERMAN_DRILL_NODES (default 2), SHERMAN_REPL
(follower count, default 2 here), SHERMAN_CHAOS_SEED,
SHERMAN_DRILL_SECS.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

import numpy as np

from common import build_cluster, pages_for_keys, setup_platform

SALT = 0xFA110FEB  # bulk-load value stamp (key ^ SALT)


def main(argv=None) -> dict:
    p = argparse.ArgumentParser()
    p.add_argument("--keys", type=int,
                   default=int(os.environ.get("SHERMAN_DRILL_KEYS", 4000)))
    # default 1 node: the drill runs THREE concurrent executors (the
    # primary's serve loop, the follower apply pump, the stale-primary
    # probe) and XLA's CPU collective rendezvous can interleave across
    # concurrent multi-device launches and deadlock — single-device
    # programs have no rendezvous.  Chip meshes pass --nodes explicitly
    # (one executor per launch group there).
    p.add_argument("--nodes", type=int,
                   default=int(os.environ.get("SHERMAN_DRILL_NODES", 1)))
    p.add_argument("--replicas", type=int,
                   default=int(os.environ.get("SHERMAN_REPL", 0) or 2))
    p.add_argument("--seed", type=int,
                   default=int(os.environ.get("SHERMAN_CHAOS_SEED", 7)))
    p.add_argument("--secs", type=float,
                   default=float(os.environ.get("SHERMAN_DRILL_SECS", 3.0)))
    p.add_argument("--dir", default=None,
                   help="drill directory (default: a tempdir)")
    a = p.parse_args(argv)
    setup_platform(a.nodes)

    from sherman_tpu import audit as A
    from sherman_tpu import obs
    from sherman_tpu.errors import ShermanError
    from sherman_tpu.models import batched
    from sherman_tpu.models.validate import check_structure_device
    from sherman_tpu.recovery import RecoveryPlane
    from sherman_tpu.replica import ReplicaGroup, StalePrimaryError
    from sherman_tpu.serve import (RetryingClient, RetryPolicy,
                                   ServeConfig, ShermanServer)
    from sherman_tpu.utils import journal as J

    t_start = time.time()
    out: dict = {"metric": "failover_drill", "seed": a.seed, "ok": False,
                 "nodes": a.nodes, "replicas": a.replicas}
    root = a.dir or tempfile.mkdtemp(prefix="sherman_failover_")
    rdir = os.path.join(root, "primary")
    rdir2 = os.path.join(root, "promoted")
    out["dir"] = root

    # -- phase 1: primary + replica group -------------------------------------
    ppn = pages_for_keys(a.keys)
    cluster, tree, eng = build_cluster(
        a.nodes, ppn, batch_per_node=512,
        locks_per_node=1024, chunk_pages=64)
    rng = np.random.default_rng(3)
    keys = np.unique(rng.integers(1, 1 << 56, int(a.keys * 1.05),
                                  dtype=np.uint64))[:a.keys]
    vals = keys ^ np.uint64(SALT)
    batched.bulk_load(tree, keys, vals)
    eng.attach_router()
    check_structure_device(tree)
    plane = RecoveryPlane(cluster, tree, eng, rdir, group_commit_ms=2.0)
    plane.checkpoint_base()
    group = ReplicaGroup(plane, a.replicas, cache_slots=2048)

    widths = (256 * a.nodes, 1024 * a.nodes)
    big = {c: 1e9 for c in ("read", "scan", "insert", "delete")}

    def front_door(engine):
        cfg = ServeConfig(widths=widths, p99_targets_ms=dict(big),
                          write_linger_ms=0.5, write_width=2048,
                          group_commit_ms=2.0)
        srv = ShermanServer(engine, cfg)
        absent = np.asarray([1 << 60], np.uint64)
        # VALUE-PRESERVING calibration (a promoted engine's state
        # already carries acked writes — re-stamping bulk values here
        # would be a silent lost update the final audit flags)
        ck = keys[:256]
        cv, cf = engine.search(ck)
        srv.start(calib_keys=keys,
                  calib_writes=(ck[cf], np.asarray(cv)[cf]),
                  calib_delete_keys=absent)
        return srv

    srv = front_door(eng)
    snap0 = obs.snapshot()

    # writer slices cover the FIRST n_writers*per keys; the last slice
    # is never written — the immutable set replica reads serve from
    n_writers, n_readers = 2, 1
    per = a.keys // (n_writers + 1)
    imm = keys[n_writers * per:]
    acked: list[dict] = [dict() for _ in range(n_writers)]
    unacked: list[dict] = [dict() for _ in range(n_writers)]
    rid_ledger: list[dict] = [dict() for _ in range(n_writers)]
    events: list[list] = [[] for _ in range(n_writers + n_readers + 1)]
    stop = threading.Event()

    gens = [0] * n_writers

    def writer(w: int, n_reqs: int):
        # bounded rounds of paced exactly-once writes: every journaled
        # write is applied R more times by the follower tier in this
        # one process, so an open-ended unthrottled writer measures
        # apply backlog, not failover (the chip-queue entry carries
        # the full-rate run); ``n_reqs == 0`` runs open-ended until
        # the stop flag — the in-flight-at-the-kill round
        my = keys[w * per:(w + 1) * per]
        cl = RetryingClient(srv, tenant=f"writer{w}",
                            policy=RetryPolicy(max_attempts=6),
                            seed=100 + w + gens[w])
        ev = events[w]
        wrng = np.random.default_rng(1000 * w + gens[w])
        done = 0
        while not stop.is_set() and (n_reqs == 0 or done < n_reqs):
            gens[w] += 1
            done += 1
            time.sleep(0.005)
            kreq = np.unique(my[wrng.integers(0, my.size, 48)])
            vreq = kreq ^ np.uint64(SALT) ^ np.uint64(gens[w] << 8)
            rid = cl.next_rid()
            t_inv = time.perf_counter()
            try:
                ok = cl.insert(kreq, vreq, rid=rid)
            except ShermanError:
                # in flight at the kill: result unknown, not owed —
                # legal for concurrent readers (open_writes below)
                for k, v in zip(kreq.tolist(), vreq.tolist()):
                    unacked[w].setdefault(k, []).append((True, v))
                continue
            t_resp = time.perf_counter()
            rid_ledger[w][rid] = (kreq, vreq, np.array(ok))
            for k, v, o in zip(kreq.tolist(), vreq.tolist(),
                               ok.tolist()):
                if o:
                    acked[w][k] = v
                    ev.append((k, A.OP_INSERT, t_inv, t_resp, v, True))

    def reader(r: int):
        cl = RetryingClient(srv, tenant=f"reader{r}",
                            policy=RetryPolicy(max_attempts=4),
                            seed=200 + r, deadline_ms=5000.0)
        ev = events[n_writers + r]
        rrng = np.random.default_rng(50 + r)
        while not stop.is_set():
            kreq = np.unique(keys[rrng.integers(0, keys.size, 64)])
            t_inv = time.perf_counter()
            try:
                got, found = cl.read(kreq)
            except ShermanError:
                continue
            t_resp = time.perf_counter()
            for k, g, f in zip(kreq.tolist(), got.tolist(),
                               found.tolist()):
                ev.append((k, A.OP_READ, t_inv, t_resp,
                           g if f else None, bool(f)))
            time.sleep(0.001)

    repl_read_fail = [0]

    def replica_reader():
        # the replica tier: certified cache hits served by a
        # caught-up follower, misses forwarded to the primary engine
        ev = events[n_writers + n_readers]
        rrng = np.random.default_rng(77)
        while not stop.is_set():
            kreq = np.unique(imm[rrng.integers(0, imm.size, 48)])
            t_inv = time.perf_counter()
            try:
                got, found = group.read(kreq)
            except ShermanError:
                repl_read_fail[0] += 1
                continue
            t_resp = time.perf_counter()
            for k, g, f in zip(kreq.tolist(),
                               np.asarray(got).tolist(),
                               np.asarray(found).tolist()):
                ev.append((k, A.OP_READ, t_inv, t_resp,
                           g if f else None, bool(f)))
            time.sleep(0.002)

    for f in group.followers:
        f.admit(imm)
    readers = [threading.Thread(target=reader, args=(r,), daemon=True)
               for r in range(n_readers)] + \
              [threading.Thread(target=replica_reader, daemon=True)]
    for t in readers:
        t.start()
    n_round = max(4, int(a.secs * 5))

    # round 1: bounded write load under the live tail
    ws = [threading.Thread(target=writer, args=(w, n_round),
                           daemon=True) for w in range(n_writers)]
    for t in ws:
        t.start()
    for t in ws:
        t.join(timeout=300)
    group.pump()

    # delta checkpoint mid-stream: rotation retires + SWEEPS the
    # shipped segment under the live tail — followers re-bootstrap
    # from the chain and must converge (pinned below).  The pump lock
    # is held across it so a background pump cannot slip through the
    # rotate->sweep window and advance the tail first (which would
    # make the sweep invisible and the re-bootstrap pin vacuous).
    with group._pump_lock:
        d1 = plane.checkpoint_delta()
    out["delta1"] = {"pages": int(d1["pages"])}
    # absorb the re-bootstrap here so the lag probe below measures a
    # steady-state shipping round, not an engine rebuild
    group.pump()

    # round 2: more acked writes on the fresh segment
    ws = [threading.Thread(target=writer, args=(w, n_round),
                           daemon=True) for w in range(n_writers)]
    for t in ws:
        t.start()
    for t in ws:
        t.join(timeout=300)
    lag_ms = group.measure_lag()

    # round 3: open-ended writers — the in-flight-at-the-kill load
    ws = [threading.Thread(target=writer, args=(w, 0), daemon=True)
          for w in range(n_writers)]
    for t in ws:
        t.start()
    time.sleep(min(0.5, a.secs / 4))

    # -- kill: no drain, torn tail at the shipping boundary -------------------
    t_kill = time.perf_counter()
    srv.kill()
    stop.set()
    for t in ws + readers:
        t.join(timeout=120)
    live_rids = {w: dict(rid_ledger[w]) for w in range(n_writers)}
    jpath = eng.journal.path
    with open(jpath, "ab") as f:  # crash mid-append: torn half-frame
        rec = J.encode_record(J.J_UPSERT,
                              np.asarray([1 << 40], np.uint64),
                              np.asarray([7], np.uint64), rid=0xDEAD)
        f.write(rec[: len(rec) // 2])

    # -- promote: fence + catch-up + highest watermark ------------------------
    rcpt = group.promote(t_dead=t_kill)
    out["promote"] = rcpt
    # the dead primary keeps writing: fenced TYPED at the durability
    # gate (the epoch check), never a silent journal fork
    try:
        eng.insert(np.asarray([1 << 41], np.uint64),
                   np.asarray([1], np.uint64))
        raise AssertionError("stale-primary write was NOT fenced")
    except ShermanError as e:
        tip = e
        while tip is not None and \
                not isinstance(tip, StalePrimaryError):
            tip = tip.__cause__
        assert isinstance(tip, StalePrimaryError) \
            or isinstance(e, StalePrimaryError), \
            f"fence raised untyped {type(e).__name__}: {e}"
    out["fenced_writes"] = group.fenced_writes
    assert group.fenced_writes >= 1

    # -- resume: new front door on the promoted engine ------------------------
    win = group.promoted
    eng2 = win.eng
    plane2 = RecoveryPlane(win.cluster, win.tree, eng2, rdir2,
                           group_commit_ms=2.0)
    plane2.checkpoint_base()  # the new primary is itself recoverable
    srv2 = front_door(eng2)
    adopted = srv2.seed_dedup(group.promoted_window())
    # first post-failover serve closes the availability gap
    _g0, f0 = srv2.submit("read", keys[:64]).result(timeout=60)
    assert np.asarray(f0).all()
    gap_ms = group.note_resumed()
    out["availability_gap_ms"] = gap_ms
    out["dedup"] = {"adopted": adopted}
    assert adopted > 0, "promotion adopted an empty exactly-once window"

    # -- RPO: every acked write served by the promoted primary ----------------
    merged_acked: dict = {}
    for d in acked:
        merged_acked.update(d)
    assert merged_acked, "drill acked no writes before the kill"
    ak = np.asarray(sorted(merged_acked), np.uint64)
    av = np.asarray([merged_acked[int(k)] for k in ak], np.uint64)
    t_inv = time.perf_counter()
    # chunk by the widest dispatch class — the audit set can exceed it
    wmax = max(widths)
    parts = [srv2.submit("read", ak[i:i + wmax]).result(timeout=120)
             for i in range(0, ak.size, wmax)]
    got = np.concatenate([np.asarray(g) for g, _ in parts])
    found = np.concatenate([np.asarray(f) for _, f in parts])
    t_resp = time.perf_counter()
    lost = int((~found).sum()) + int((got[found] != av[found]).sum())
    post_events = [(int(k), A.OP_READ, t_inv, t_resp,
                    int(g) if f else None, bool(f))
                   for k, g, f in zip(ak.tolist(), got.tolist(),
                                      found.tolist())]
    # untouched-key probe: bulk values still served verbatim
    probe = keys[~np.isin(keys, ak)][:: max(1, a.keys // 512)]
    got, found = srv2.submit("read", probe).result(timeout=120)
    lost += int((~found).sum()) + int(
        (got[found] != (probe ^ np.uint64(SALT))[found]).sum())
    out["lost_acks"] = lost
    assert lost == 0, f"{lost} acked ops lost across the failover"

    # -- retry across the failover: re-ack, never re-apply --------------------
    duplicate_acks = 0
    retried = 0
    for w in range(n_writers):
        sample = list(live_rids[w].items())[-4:]
        for rid, (kreq, vreq, ok0) in sample:
            if not ok0.any():
                continue
            retried += 1
            # 1) move the keys PAST the old write (fresh rid)
            vnew = kreq ^ np.uint64(SALT) ^ np.uint64(0x7777_0000)
            t_inv = time.perf_counter()
            ok2 = srv2.submit("insert", kreq, vnew,
                              tenant=f"writer{w}",
                              rid=(0x7777 << 32) | (rid & 0xFFFFFFFF)
                              ).result(timeout=60)
            t_resp = time.perf_counter()
            for k, v, o in zip(kreq.tolist(), vnew.tolist(),
                               ok2.tolist()):
                if o:
                    merged_acked[k] = v
                    post_events.append((k, A.OP_INSERT, t_inv,
                                        t_resp, v, True))
            # 2) retry the PRE-KILL rid with its original payload: the
            # promoted window must re-ack the ORIGINAL result
            fut = srv2.submit("insert", kreq, vreq,
                              tenant=f"writer{w}", rid=rid)
            okr = fut.result(timeout=60)
            if not fut.deduped or not np.array_equal(okr, ok0):
                duplicate_acks += 1
                continue
            got, found = srv2.submit("read", kreq).result(timeout=60)
            stomped = int(np.sum(found & ok2 & (got == vreq)
                                 & (vreq != vnew)))
            if stomped:
                duplicate_acks += 1
    out["retry_across_failover"] = {"retried": retried,
                                    "dedup_hits": srv2.dedup_hits}
    out["duplicate_acks"] = duplicate_acks
    assert retried > 0, "drill retried nothing across the failover"
    assert duplicate_acks == 0, \
        f"{duplicate_acks} retried writes re-applied (lost updates)"
    srv2.drain()
    plane2.close()

    # -- offline linearizability over BOTH sides of the failover --------------
    all_events = [e for ev in events for e in ev] + post_events
    initial = {int(k): (True, int(v)) for k, v in zip(keys, vals)}
    open_w: dict = {}
    for d in unacked:
        for k, outs in d.items():
            open_w.setdefault(k, []).extend(outs)
    verdict = A.check_events(all_events, initial=initial,
                             open_writes=open_w)
    out["audit"] = {
        "events": verdict["events"],
        "keys": verdict["keys"],
        "reads_checked": verdict["reads"],
        "violations": len(verdict["violations"]),
        "linearizable": bool(verdict["linearizable"]),
    }
    out["linearizable"] = bool(verdict["linearizable"])
    if verdict["violations"]:
        out["audit"]["first_violations"] = verdict["violations"][:3]
    assert verdict["linearizable"], \
        f"history not linearizable: {verdict['violations'][:3]}"
    assert verdict["reads"] > 0, "audit checked no reads"
    jsonl = os.path.join(root, "history.jsonl")
    A.dump_jsonl(all_events, jsonl)
    out["history_jsonl"] = jsonl

    # -- the replication receipt ----------------------------------------------
    st = group.stats()
    out["repl"] = {
        "followers": st["followers"],
        "applied_records": st["applied_records"],
        "applied_rows": st["applied_rows"],
        "absorbed_acks": st["absorbed_acks"],
        "rebootstraps": st["rebootstraps"],
        "torn_waits": st["torn_waits"],
        "lag_ms": round(lag_ms, 2),
        "reads_served": st["reads_served"],
        "reads_forwarded": st["reads_forwarded"],
        "read_failures": repl_read_fail[0],
        "epoch": st["epoch"],
        "watermark": {"link": st["watermark_link"],
                      "seq": st["watermark_seq"]},
    }
    assert st["applied_records"] > 0, "the tail shipped nothing"
    assert st["rebootstraps"] >= a.replicas, \
        "the mid-stream sweep never forced a re-bootstrap"
    assert st["reads_served"] > 0, "no replica-served reads"

    d = obs.delta(snap0, obs.snapshot())
    out["obs"] = {k: round(float(d[k]), 2) for k in sorted(d)
                  if k in ("repl.applied_records", "repl.promotions",
                           "repl.fenced_writes", "repl.lag_ms",
                           "repl.availability_gap_ms")}
    out["elapsed_s"] = round(time.time() - t_start, 1)
    out["ok"] = True
    line = json.dumps(out)
    print(line)
    receipt = os.environ.get("SHERMAN_FAILOVER_RECEIPT")
    if receipt:
        with open(receipt, "w") as f:
            f.write(line + "\n")
    print("FAILOVER-DRILL PASS", file=sys.stderr)
    return out


if __name__ == "__main__":
    main()
