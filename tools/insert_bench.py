#!/usr/bin/env python
"""Fresh-insert split-storm benchmark — BASELINE config 3 at scale.

The reference's config 3 is "insert-only: bulk-load, leaf-split heavy"
(``test/benchmark.cpp`` with kReadRatio=0; split machinery
``src/Tree.cpp:922-963``, parent ascent ``:980-987``).  The existing
``tools/benchmark.py 1 0 ...`` row measures the update-heavy steady state
(writes over the warm set); THIS driver measures sustained NEW-key
insertion: an 80-90%-full tree absorbs a stream of fresh keys with
device-side leaf splits, ``flush_parents`` and router ``note_split`` all
inside the timed loop.

    python tools/insert_bench.py [--keys 10000000] [--fresh 3000000]
        [--chunk 1048576] [--fill 0.9] [--split-slots 16384] [--nodes 1]

Key layout: warm and fresh keys come from one synthetic keyspace
(``mix64(rank ^ salt)``, native.synthetic_keyspace) so fresh keys
interleave UNIFORMLY across the warm tree — every leaf sees inserts and
the storm splits leaves everywhere, not just an append tail (appending
past the max key would serialize on the rightmost leaf, the same
last-leaf lock serialization the reference pays for appends).

Prints per-chunk progress and ONE summary JSON line:
    fresh_insert_ops_s, splits_s, device_splits, host_path (must be ~0
    at steady state), rounds_per_chunk, parent_flushes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from common import setup_platform  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=10_000_000,
                    help="warm tree size (bulk-loaded)")
    ap.add_argument("--fresh", type=int, default=3_000_000,
                    help="fresh keys inserted during the timed storm")
    ap.add_argument("--chunk", type=int, default=1_048_576,
                    help="fresh keys per engine insert call")
    ap.add_argument("--fill", type=float, default=0.9,
                    help="bulk-load leaf fill (higher = more splits)")
    ap.add_argument("--split-slots", type=int, default=16_384,
                    help="fresh-page grant slots per node per round")
    ap.add_argument("--nodes", type=int, default=1)
    ap.add_argument("--verify", action="store_true",
                    help="post-storm: search every fresh key + device "
                         "structure validation")
    args = ap.parse_args()

    jax = setup_platform(args.nodes)
    jax.config.update("jax_compilation_cache_dir", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from sherman_tpu import native
    from sherman_tpu.cluster import Cluster
    from sherman_tpu.config import LEAF_CAP, DSMConfig
    from sherman_tpu.models import batched
    from sherman_tpu.models.btree import Tree

    total_keys = args.keys + args.fresh
    if native.available():
        salt = 0x5E17_AB1E_5A17
        while True:
            try:
                _, rank_to_key = native.synthetic_keyspace(total_keys, salt)
                break
            except ValueError:
                salt += 1
    else:
        rng0 = np.random.default_rng(7)
        rank_to_key = np.unique(rng0.integers(
            1, (1 << 63), int(total_keys * 1.05),
            dtype=np.uint64))[:total_keys]
        rng0.shuffle(rank_to_key)
    warm = np.sort(rank_to_key[: args.keys])
    fresh = rank_to_key[args.keys:]
    rng = np.random.default_rng(13)
    rng.shuffle(fresh)  # arrival order uncorrelated with key order
    vals_of = lambda k: k ^ np.uint64(0xBEEF)

    # pool: warm leaves at --fill + post-storm growth + internals + slack
    per_leaf = max(1, int(LEAF_CAP * args.fill))
    est = int(total_keys / per_leaf * 1.35) + 8192
    pages = 1 << max(14, (est - 1).bit_length())
    # host_step_capacity: flush_parents posts ~2 rows per touched parent
    # page; a split storm touches thousands per round, and the default 64
    # rows/step would serialize the flush into dozens of tunnel round
    # trips per round
    cfg = DSMConfig(machine_nr=args.nodes, pages_per_node=pages,
                    locks_per_node=65_536, step_capacity=args.chunk,
                    chunk_pages=4096, host_step_capacity=8192)
    cluster = Cluster(cfg)
    tree = Tree(cluster)
    eng = batched.BatchedEngine(tree, batch_per_node=args.chunk,
                                split_slots=args.split_slots)
    # flush parent entries once per chunk, not per round: the router's
    # note_split keeps mid-chunk descents short, and each flush pass is
    # several host round trips (seconds each over the access tunnel)
    eng.parent_flush_threshold = eng.split_slots
    t0 = time.time()
    stats0 = batched.bulk_load(tree, warm, vals_of(warm), fill=args.fill)
    router = eng.attach_router()
    print(f"# warm load {time.time() - t0:.1f}s {stats0} "
          f"router_lb={router.lb} split_slots={eng.split_slots}",
          file=sys.stderr)

    # compile warmup OUTSIDE the timed window: one small chunk exercises
    # the no-grant round-0 kernel, the with-grant split kernel and the
    # flush_parents machinery (first compiles cost ~20-40 s each over the
    # remote-compile path; the storm then measures execution)
    w = max(4096, args.chunk // 64)
    t0 = time.time()
    ws = eng.insert(fresh[:w], vals_of(fresh[:w]))
    print(f"# compile-warm chunk ({w} keys) {time.time() - t0:.1f}s {ws}",
          file=sys.stderr)

    # ---- the storm: everything inside the timed loop ----
    agg = {"applied": 0, "superseded": 0, "host_path": 0, "rounds": 0,
           "st_locked": 0, "device_splits": 0}
    splits_before = 0
    chunks = 0
    t0 = time.time()
    for i in range(w, fresh.size, args.chunk):
        ck = fresh[i: i + args.chunk]
        st = eng.insert(ck, vals_of(ck))
        for k in agg:
            agg[k] += st.get(k, 0)
        chunks += 1
        dt = time.time() - t0
        done_n = i + ck.size - w
        print(f"#   chunk {chunks}: +{ck.size} keys, "
              f"splits {agg['device_splits']}, rounds {st['rounds']}, "
              f"host_path {agg['host_path']}, "
              f"{done_n / dt / 1e6:.2f} M ops/s cum", file=sys.stderr)
    elapsed = time.time() - t0
    n_storm = fresh.size - w

    ops_s = n_storm / elapsed
    splits_s = (agg["device_splits"] - splits_before) / elapsed
    out = {
        "metric": "fresh_insert_split_storm",
        "value": round(ops_s),
        "unit": "ops/s",
        "keys_warm": args.keys,
        "keys_fresh": n_storm,
        "fill": args.fill,
        "elapsed_s": round(elapsed, 2),
        "fresh_insert_ops_s": round(ops_s),
        "device_splits": agg["device_splits"],
        "splits_s": round(splits_s),
        "host_path": agg["host_path"],
        "st_locked": agg["st_locked"],
        "rounds_per_chunk": round(agg["rounds"] / max(1, chunks), 2),
        "router_splits_noted": router.splits_noted,
        "chunk": args.chunk,
        "split_slots": eng.split_slots,
        "nodes": args.nodes,
    }

    if args.verify:
        t0 = time.time()
        got, found = eng.search(fresh)
        assert found.all(), f"storm lost {int((~found).sum())} fresh keys"
        np.testing.assert_array_equal(got, vals_of(fresh))
        sample = warm[:: max(1, warm.size // 1_000_000)]
        got, found = eng.search(sample)
        assert found.all(), "storm lost warm keys"
        np.testing.assert_array_equal(got, vals_of(sample))
        from sherman_tpu.models.validate import check_structure_device
        info = check_structure_device(tree)
        assert info["keys"] == total_keys, info
        out["verified"] = True
        print(f"# verify {time.time() - t0:.1f}s: every fresh+sampled-warm "
              f"key present, structure valid ({info['keys']} keys)",
              file=sys.stderr)

    print(f"# storm: {n_storm} fresh keys in {elapsed:.1f}s -> "
          f"{ops_s / 1e6:.2f} M inserts/s, {agg['device_splits']} device "
          f"splits ({splits_s:.0f}/s), host_path {agg['host_path']}, "
          f"{tree.dsm.counter_snapshot()}", file=sys.stderr)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
