#!/usr/bin/env python
"""Staged-step anatomy: decompose the device-staged step and put it
side by side with the host-staged serve it must match.

Round-5 left a measured-but-unexplained 2x ("known headroom" in
BENCHMARKS.md): the staged step ran ~124 ms/step while the identical
routed serve fed host-staged inputs measured 72-84 ms.  This driver is
the attribution tool for that gap:

- builds the staged step in any fusion mode (``FUSION`` env:
  aligned | pipelined | chained | fused — see ``config.staged_fusion``),
- times the FULL pipelined step (bounded dispatch window, the honest
  loop shape bench.py runs),
- attributes per-phase costs with the chained-delta method
  (``step.phase_profile``: K and 2K data-dependent repetitions per
  program, cost = (t_2K - t_K)/K — per-call timings through a remote
  access tunnel measure the tunnel, see tools/profile_insert.py),
- runs the HOST-STAGED comparator: the engine's combined-search
  fan-out program on one pre-staged batch of the same width — in
  ``aligned`` mode this is the SAME compiled program object the staged
  serve dispatches, so staged-vs-host serve cost is an apples-to-apples
  diff by construction,
- records every region as an obs span / histogram and prints the
  side-by-side prep-vs-serve table plus ONE JSON line,
- runs the MODE WALL table (round-8): aligned vs ``pipelined`` (the
  two-deep software pipeline — verify k-1 / prep k+1 dispatched behind
  serve k) through the same bounded-window loop, each with its
  ``bubble_ms`` (wall − serve: the work not hidden behind the serve
  bound) and ``overlap_efficiency`` (1 − wall/(prep+serve+verify))
  against ONE shared phase attribution — the JSON ``modes`` block is
  the CPU receipt for BENCHMARKS' Round-8 and the input to the queued
  pipelined-vs-aligned chip A/B.

Env knobs: KEYS (10 M), B (4 M), DEVB, K (delta reps, 8), FUSION,
SAMPLER (analytic), W (dispatch window, 8), STEPS (pipelined steps, 24),
MODES (mode-wall table, default "aligned,pipelined"; "" disables; a
"+cache" suffix — e.g. "aligned+cache" — runs that mode with the
hot-key leaf cache's probe program chained in and the residual serve
width sized from a 2-step warmup's measured misses (RESID env
overrides), attributed with its own cache_probe/residual-serve phase
walls so the probe cost AND the serve shrink are priced next to the
uncached modes).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _host_staged_batch(native, router, n_keys, batch, dev_b, theta, salt):
    """One host-staged batch (khi, klo, start, active, inv) — the
    throughput-phase prep: native BatchPrep when available, else the
    numpy unique+inverse fallback (CPU smoke runs)."""
    from sherman_tpu.ops import bits

    if native.available():
        prep_h = native.BatchPrep(batch, dev_b, n_keys, theta, seed=11,
                                  salt=salt)
        buf = prep_h.buffers()
        b = prep_h.run_zipf(None, buf, router.table_np, router.shift)
        return b.khi, b.klo, b.start, b.active.view(bool), b.inv
    from sherman_tpu.workload.zipf import ZipfGen, uniform_ranks
    if theta > 0:
        ranks = ZipfGen(n_keys, theta, seed=11).sample(batch)
    else:
        ranks = uniform_ranks(n_keys, batch, np.random.default_rng(11))
    keys = bits.mix64_np(ranks.astype(np.uint64) ^ np.uint64(salt))
    uk, inv = np.unique(keys, return_inverse=True)
    assert uk.size <= dev_b, (uk.size, dev_b)
    pad = (0, dev_b - uk.size)
    khi, klo = bits.keys_to_pairs(np.pad(uk, pad))
    act = np.zeros(dev_b, bool)
    act[:uk.size] = True
    start = np.pad(router.host_start(*bits.keys_to_pairs(uk)), pad)
    return khi, klo, start, act, inv.astype(np.int32)


def main():
    import jax

    jax.config.update("jax_compilation_cache_dir", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache"))

    from sherman_tpu import native, obs
    from sherman_tpu import config as C
    from sherman_tpu.cluster import Cluster
    from sherman_tpu.config import DSMConfig, LEAF_CAP, TreeConfig
    from sherman_tpu.models import batched
    from sherman_tpu.models.btree import Tree
    from sherman_tpu.ops import bits
    from sherman_tpu.workload import device_prep

    n_keys = int(os.environ.get("KEYS", 10_000_000))
    batch = int(os.environ.get("B", 4_194_304))
    theta = float(os.environ.get("THETA", 0.99))
    fusion = os.environ.get("FUSION") or C.staged_fusion()
    sampler = os.environ.get("SAMPLER", "analytic")
    K = int(os.environ.get("K", 8))
    W = int(os.environ.get("W", 8))
    n_steps = int(os.environ.get("STEPS", 24))
    salt = 0x5E17_AB1E_5A17
    fill = 0.75
    per_leaf = max(1, int(LEAF_CAP * fill))
    est_pages = int(n_keys / per_leaf * 1.10) + 8192
    pages = 1 << max(14, (est_pages - 1).bit_length())
    cfg = DSMConfig(machine_nr=1, pages_per_node=pages,
                    locks_per_node=65_536, step_capacity=batch,
                    chunk_pages=4096)
    cluster = Cluster(cfg)
    tree = Tree(cluster)
    eng = batched.BatchedEngine(tree, batch_per_node=batch,
                                tcfg=TreeConfig(sibling_chase_budget=1))
    if native.available():
        keys, _ = native.synthetic_keyspace(n_keys, salt)
    else:
        ranks = np.arange(n_keys, dtype=np.uint64)
        keys = np.sort(bits.mix64_np(ranks ^ np.uint64(salt)))
    t0 = time.time()
    with obs.span("profile.bulk_load", keys=n_keys):
        batched.bulk_load(tree, keys, keys ^ np.uint64(0xDEADBEEF),
                          fill=fill)
    eng.attach_router()
    print(f"# bulk_load {time.time() - t0:.1f}s", file=sys.stderr)

    dev_b = int(os.environ.get("DEVB", min(batch, 1_097_728 + 16384)))
    step, (new_carry, table_d, rtable_d, rkey_d) = \
        device_prep.make_staged_step(eng, n_keys=n_keys, theta=theta,
                                     salt=salt, batch=batch, dev_b=dev_b,
                                     sampler=sampler, fusion=fusion)
    dsm = eng.dsm
    pool, counters = dsm.pool, dsm.counters

    # A. full staged step, pipelined with the bounded dispatch window
    # bench.py uses (PJRT allocates output buffers at enqueue; block on
    # the LAST program's carry from W steps back)
    from collections import deque

    def windowed_wall(stp, nc, box, span_name):
        """Bounded-window wall per step of one staged-step build,
        chained-delta timed (STEPS and 2*STEPS windowed dispatches,
        cost = (t_2K - t_K)/K — same methodology as the phases, so
        the loop-invocation constant [first-dispatch program load,
        carry staging] cancels and wall-vs-phase comparisons are
        apples to apples).  Receipts verified on every invocation
        (drained — the pipelined mode's receipts lag a batch until
        ``stp.drain``)."""
        state = {}

        def loop(k):
            carry = nc()
            pend = deque()
            for _ in range(k):
                box["c"], carry = stp(pool, box["c"], table_d,
                                      rtable_d, rkey_d, carry)
                pend.append(carry[1])
                if len(pend) > W:
                    jax.block_until_ready(pend.popleft())
            carry = stp.drain(carry)
            jax.block_until_ready(carry)
            assert int(np.asarray(carry[1])) == 1, \
                "windowed loop: unique overflow"
            assert int(np.asarray(carry[2])) == k * batch, \
                "windowed loop: receipts failed"
            state["steps"] = k

        # warm BOTH carry variants before the delta: step 1 consumes a
        # fresh new_carry() (host-put shardings), step 2+ the threaded
        # program outputs — two jit cache entries, and the second's
        # trace must not land inside the first timed invocation
        loop(2)
        with obs.span(span_name, steps=n_steps, fusion=stp.fusion):
            wall = device_prep._delta_ms(loop, n_steps)
        assert state["steps"] == 2 * n_steps  # every batch verified
        return wall

    cbox = {"c": counters}
    full_ms = windowed_wall(step, new_carry, cbox,
                            "profile.full_step_pipelined")
    counters = cbox["c"]
    obs.histogram("staged.full_step_ms").record(full_ms)
    print(f"{'full_step':20s} {full_ms:9.1f} ms/step (windowed W={W}, "
          f"chained-delta, receipts verified)", file=sys.stderr)

    # B. per-phase attribution (chained-delta; obs histograms under
    # staged.<phase>_ms)
    with obs.span("profile.phase_attribution", reps=K, fusion=fusion):
        phase_ms, counters = step.phase_profile(pool, counters, table_d,
                                                rtable_d, rkey_d, reps=K)
    device_prep.record_phase_obs("staged", phase_ms)
    for name, ms in phase_ms.items():
        if name == "overlap_efficiency":  # a ratio, not a wall
            print(f"{name:20s} {ms:9.2f}", file=sys.stderr)
        else:
            print(f"{name:20s} {ms:9.1f} ms", file=sys.stderr)

    # C. host-staged serve comparator: the engine fan-out program on one
    # pre-staged batch of the same width.  In 'aligned' mode this is the
    # same compiled program object as the staged serve.
    hkhi, hklo, hstart, hact, hinv = _host_staged_batch(
        native, eng.router, n_keys, batch, dev_b, theta, salt)
    shard = dsm.shard
    d = (jax.device_put(hkhi, shard), jax.device_put(hklo, shard),
         jax.device_put(hstart, shard), jax.device_put(hact, shard),
         jax.device_put(hinv, shard))
    fn = eng._get_search_fanout(eng._iters())
    root = np.int32(tree._root_addr)
    box = {"c": counters}

    def serve_host_loop(k):
        out = None
        for _ in range(k):
            box["c"], done, found, vhi, vlo = fn(
                pool, box["c"], d[0], d[1], root, d[3], d[2], d[4])
            out = found
        jax.block_until_ready(out)

    with obs.span("profile.serve_host_staged", reps=K):
        serve_host_ms = device_prep._delta_ms(serve_host_loop, K)
    counters = box["c"]
    obs.histogram("staged.serve_host_staged_ms").record(serve_host_ms)
    dsm.counters = counters

    # side-by-side: what the staged loop pays vs the host-staged serve.
    # Only the serve-bearing phase is comparable: aligned's serve_fanout
    # (the SAME compiled program as the comparator) and chained's
    # serve_fanout_verify (serve + ~elementwise verify).  A fused run
    # has no separable serve — its ratio would fold prep+verify in and
    # read as a phantom serve regression, so it is not published.
    staged_serve = phase_ms.get("serve_fanout",
                                phase_ms.get("serve_fanout_verify"))
    print("#\n# side-by-side (ms): staged step vs host-staged serve",
          file=sys.stderr)
    print(f"# {'phase':22s} {'staged':>9s} {'host-staged':>12s}",
          file=sys.stderr)
    print(f"# {'prep':22s} {phase_ms.get('prep', float('nan')):9.1f} "
          f"{'(host prep untimed)':>12s}", file=sys.stderr)
    if staged_serve is not None:
        print(f"# {'serve(+fanout)':22s} {staged_serve:9.1f} "
              f"{serve_host_ms:12.1f}", file=sys.stderr)
    else:
        print(f"# {'fused prep+serve+verify':22s} "
              f"{phase_ms['fused_step']:9.1f} {serve_host_ms:12.1f}",
              file=sys.stderr)
    if "verify" in phase_ms:
        print(f"# {'verify':22s} {phase_ms['verify']:9.1f} "
              f"{'—':>12s}", file=sys.stderr)
    print(f"# {'full step (pipelined)':22s} {full_ms:9.1f} "
          f"{'—':>12s}", file=sys.stderr)
    gap = (staged_serve / serve_host_ms
           if staged_serve is not None and serve_host_ms else None)
    if gap is not None:
        same = (" (aligned dispatches the SAME program: any residual is"
                " input production, not program shape)"
                if fusion == "aligned" else
                " (chained serve also folds the ~elementwise verify)")
        print(f"# staged-serve / host-staged-serve = {gap:.2f}x{same}",
              file=sys.stderr)
    else:
        print("# no serve-only ratio for fused runs (one program; "
              "prep+verify inseparable)", file=sys.stderr)

    # D. mode wall table (round-8): aligned vs the two-deep pipelined
    # form through the SAME bounded-window loop.  The three compiled
    # programs are SHARED between the modes by construction (pipelined
    # reuses the aligned serve object), so ONE phase attribution prices
    # both: bubble_ms = wall - serve (work not hidden behind the serve
    # bound), overlap_efficiency = 1 - wall/(prep+serve+verify).
    modes_env = os.environ.get("MODES", "aligned,pipelined")
    modes = {}
    if modes_env.strip():
        want = [m.strip() for m in modes_env.split(",") if m.strip()]
        # "+cache" suffix (e.g. "aligned+cache"): the same fusion mode
        # with the hot-key leaf cache's probe program chained in, so
        # the probe's cost is attributable per phase next to the
        # uncached walls.  The cache is built once, prefilled with the
        # analytically hottest ranks (the zipf sampler's own ranking).
        lc_box = {"lc": None}

        def _leaf_cache():
            if lc_box["lc"] is None:
                lc = eng.attach_leaf_cache()
                lc.fill(bits.mix64_np(
                    np.arange(min(lc.capacity, n_keys),
                              dtype=np.uint64) ^ np.uint64(salt)))
                lc_box["lc"] = lc
            return lc_box["lc"]

        by_mode = {}
        for spec_m in want:
            base_m, _, suffix = spec_m.partition("+")
            if suffix not in ("", "cache"):
                raise SystemExit(f"MODES entry {spec_m!r}: want "
                                 "<fusion> or <fusion>+cache")
            cache_on = suffix == "cache"
            resid = None
            if cache_on:
                # size the residual serve width from a 2-step warmup of
                # a full-width sizing build (bench.py's cap-tightening
                # dance — the serve must SHRINK for the hits to pay;
                # RESID env overrides).  Overflow voids via the ok
                # receipt, which windowed_wall asserts on.
                resid_env = os.environ.get("RESID")
                if resid_env:
                    resid = int(resid_env)
                else:
                    sz, (nc_sz, *_r) = device_prep.make_staged_step(
                        eng, n_keys=n_keys, theta=theta, salt=salt,
                        batch=batch, dev_b=dev_b, sampler=sampler,
                        fusion=base_m,
                        staged=(table_d, rtable_d, rkey_d),
                        leaf_cache=_leaf_cache())
                    c_sz = nc_sz()
                    cbox = {"c": counters}
                    for _ in range(2):
                        cbox["c"], c_sz = sz(pool, cbox["c"], table_d,
                                             rtable_d, rkey_d, c_sz)
                    c_sz = sz.drain(c_sz)
                    jax.block_until_ready(c_sz)
                    counters = cbox["c"]
                    miss = (int(np.asarray(c_sz[3]))
                            - int(np.asarray(c_sz[6]))) // 2
                    # quantum scales down with dev_b so smoke-scale
                    # runs still show a real shrink (bench.py's 8192
                    # matters only at its multi-M widths)
                    q = min(8192, max(256, dev_b // 8))
                    resid = min(dev_b,
                                -(-int(max(1, miss) * 1.05) // q) * q)
                    print(f"# {spec_m}: residual serve width {resid} "
                          f"of {dev_b} ({miss} measured misses/step)",
                          file=sys.stderr)
            if base_m == fusion and not cache_on:
                by_mode[spec_m] = (step, new_carry)
            else:
                s2, (nc2, *_r) = device_prep.make_staged_step(
                    eng, n_keys=n_keys, theta=theta, salt=salt,
                    batch=batch, dev_b=dev_b, sampler=sampler,
                    fusion=base_m, staged=(table_d, rtable_d, rkey_d),
                    leaf_cache=_leaf_cache() if cache_on else None,
                    dev_b_resid=resid)
                by_mode[spec_m] = (s2, nc2)
        if {"prep", "serve_fanout", "verify"} <= set(phase_ms):
            attr = phase_ms
        else:  # anatomy ran chained/fused: attribute the shared
            #    3-program form once for the table
            s_al, nc_al = by_mode.get("aligned", (None, None))
            if s_al is None:
                s_al, (nc_al, *_r) = device_prep.make_staged_step(
                    eng, n_keys=n_keys, theta=theta, salt=salt,
                    batch=batch, dev_b=dev_b, sampler=sampler,
                    fusion="aligned", staged=(table_d, rtable_d,
                                              rkey_d))
            with obs.span("profile.mode_attribution", reps=K):
                attr, counters = s_al.phase_profile(
                    pool, counters, table_d, rtable_d, rkey_d, reps=K)
        serial = attr["prep"] + attr["serve_fanout"] + attr["verify"]
        print(f"#\n# mode walls (W={W}, {n_steps} steps; serial sum "
              f"{serial:.1f} ms = prep {attr['prep']:.1f} + serve "
              f"{attr['serve_fanout']:.1f} + verify "
              f"{attr['verify']:.1f})", file=sys.stderr)
        print(f"# {'mode':16s} {'wall_ms':>9s} {'bubble_ms':>10s} "
              f"{'overlap_eff':>12s}", file=sys.stderr)
        attr_cache = None  # one shared attribution per cache-ness
        for mode in want:
            s2, nc2 = by_mode[mode]
            cache_on = bool(getattr(s2, "cache", False))
            if cache_on and attr_cache is None:
                # cache modes get their OWN attribution: the serve
                # phase measures the RESIDUAL batch and cache_probe is
                # a fourth program
                with obs.span("profile.mode_attribution_cache", reps=K):
                    attr_cache, counters = s2.phase_profile(
                        pool, counters, table_d, rtable_d, rkey_d,
                        reps=K)
            a = attr_cache if cache_on else attr
            cbox = {"c": counters}
            wall = (full_ms if mode == fusion else windowed_wall(
                s2, nc2, cbox, f"profile.mode_wall_{mode}"))
            counters = cbox["c"]
            rec = device_prep.overlap_receipt(
                a["prep"] + a.get("cache_probe", 0.0),
                a["serve_fanout"], a["verify"], wall)
            row = {"wall_ms": round(rec["wall_ms"], 2),
                   "bubble_ms": round(rec["bubble_ms"], 2),
                   "overlap_efficiency":
                   round(rec["overlap_efficiency"], 3)}
            if cache_on:
                row["cache_probe_ms"] = round(
                    a.get("cache_probe", 0.0), 2)
                row["serve_fanout_ms"] = round(a["serve_fanout"], 2)
            modes[mode] = row
            obs.histogram(f"staged.{mode}_wall_ms").record(wall)
            print(f"# {mode:16s} {row['wall_ms']:9.1f} "
                  f"{row['bubble_ms']:10.1f} "
                  f"{row['overlap_efficiency']:12.3f}", file=sys.stderr)
    dsm.counters = counters

    out = {
        "metric": "staged_step_anatomy",
        "fusion": fusion,
        "sampler": step.sampler,
        "n_programs": step.n_programs,
        "full_step_ms": round(full_ms, 2),
        "phase_ms": {k: round(v, 2) for k, v in phase_ms.items()},
        "serve_host_staged_ms": round(serve_host_ms, 2),
        # serve-vs-serve only (aligned/chained); null on fused runs —
        # there is no separable staged serve to compare
        "staged_vs_host_serve_ratio": round(gap, 3)
        if gap is not None else None,
        # per-mode bounded-window walls + overlap receipts (round-8):
        # {mode: {wall_ms, bubble_ms, overlap_efficiency}} — the
        # pipelined-vs-aligned side of the queued chip A/B
        "modes": modes or None,
        "pipeline_depth": step.pipeline_depth,
        "keys": n_keys, "batch": batch, "dev_b": dev_b,
        "window": W, "delta_reps": K,
        # per-phase obs spans/histograms of this run (staged.* keys)
        "obs": obs.obs_section(),
    }
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
