#!/usr/bin/env python
"""Decompose the fused device-staged step on the real chip: full step vs
prep-only vs serve-only, same shard_map structure, same tree."""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_compilation_cache_dir", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache"))

    from sherman_tpu import native
    from sherman_tpu.cluster import Cluster
    from sherman_tpu.config import DSMConfig, LEAF_CAP, TreeConfig
    from sherman_tpu.models import batched
    from sherman_tpu.models.btree import Tree
    from sherman_tpu.workload import device_prep

    n_keys = int(os.environ.get("KEYS", 10_000_000))
    batch = int(os.environ.get("B", 4_194_304))
    theta = 0.99
    salt = 0x5E17_AB1E_5A17
    fill = 0.75
    per_leaf = max(1, int(LEAF_CAP * fill))
    est_pages = int(n_keys / per_leaf * 1.10) + 8192
    pages = 1 << max(14, (est_pages - 1).bit_length())
    cfg = DSMConfig(machine_nr=1, pages_per_node=pages,
                    locks_per_node=65_536, step_capacity=batch,
                    chunk_pages=4096)
    cluster = Cluster(cfg)
    tree = Tree(cluster)
    eng = batched.BatchedEngine(tree, batch_per_node=batch,
                                tcfg=TreeConfig(sibling_chase_budget=1))
    keys, _ = native.synthetic_keyspace(n_keys, salt)
    t0 = time.time()
    batched.bulk_load(tree, keys, keys ^ np.uint64(0xDEADBEEF), fill=fill)
    eng.attach_router()
    print(f"bulk_load {time.time() - t0:.1f}s", flush=True)

    dev_b = int(os.environ.get("DEVB", 1_097_728 + 16384))
    step, (new_carry, table_d, rtable_d, rkey_d) = \
        device_prep.make_staged_step(eng, n_keys=n_keys, theta=theta,
                                     salt=salt, batch=batch, dev_b=dev_b)
    dsm = eng.dsm
    pool, counters = dsm.pool, dsm.counters
    K = int(os.environ.get("K", 8))

    def timeit(name, fn, *args, reps=K):
        out = fn(*args)
        jax.block_until_ready(out)
        t0 = time.time()
        o = out
        for _ in range(reps):
            o = fn(*args)
        jax.block_until_ready(o)
        print(f"{name:16s} {(time.time() - t0) / reps * 1e3:9.1f} ms",
              flush=True)
        return out

    # A. full fused step
    carry = new_carry()
    out = step(pool, counters, table_d, rtable_d, rkey_d, carry)
    jax.block_until_ready(out)
    counters, carry = out
    t0 = time.time()
    for _ in range(K):
        counters, carry = step(pool, counters, table_d, rtable_d,
                               rkey_d, carry)
    jax.block_until_ready(carry)
    print(f"{'full_step':16s} {(time.time() - t0) / K * 1e3:9.1f} ms",
          flush=True)
    dsm.counters = counters

    # A2. the two chained programs separately
    carry = new_carry()
    _, *arrs = step.jprep(table_d, rtable_d, rkey_d, carry[0])
    jax.block_until_ready(arrs[0])
    t0 = time.time()
    for i in range(K):
        si, *arrs2 = step.jprep(table_d, rtable_d, rkey_d,
                                np.uint32(i + 1))
    jax.block_until_ready(arrs2[0])
    print(f"{'jprep':16s} {(time.time() - t0) / K * 1e3:9.1f} ms",
          flush=True)
    rc = tuple(carry[1:])
    ctr0 = dsm.counters
    ctr0, rc = step.jserve(pool, ctr0, rc, *arrs2)
    jax.block_until_ready(rc)
    t0 = time.time()
    for i in range(K):
        _, *arrs2 = step.jprep(table_d, rtable_d, rkey_d, np.uint32(i))
        jax.block_until_ready(arrs2[0])
        t1 = time.time()
        ctr0, rc = step.jserve(pool, ctr0, rc, *arrs2)
        jax.block_until_ready(rc)
        print(f"  jserve rep {i}: {(time.time() - t1) * 1e3:9.1f} ms",
              flush=True)
    dsm.counters = ctr0

    # (prep-only timing: step.jprep above — the profiler reuses the
    # SHIPPED programs instead of copying the pipeline)

    # C. serve-only: the throughput-phase fanout kernel on one host-
    # staged batch of the same width
    prep_h = native.BatchPrep(batch, dev_b, n_keys, theta, seed=11,
                              salt=salt)
    buf = prep_h.buffers()
    b = prep_h.run_zipf(None, buf, eng.router.table_np, eng.router.shift)
    fn = eng._get_search_fanout(eng._iters())
    shard = dsm.shard
    d = (jax.device_put(b.khi, shard), jax.device_put(b.klo, shard),
         jax.device_put(b.start, shard),
         jax.device_put(b.active.view(bool), shard),
         jax.device_put(b.inv, shard))
    root = np.int32(tree._root_addr)
    ctr = dsm.counters

    out = fn(pool, ctr, d[0], d[1], root, d[3], d[2], d[4])
    jax.block_until_ready(out[2])
    ctr = out[0]
    t0 = time.time()
    for _ in range(K):
        out = fn(pool, ctr, d[0], d[1], root, d[3], d[2], d[4])
        ctr = out[0]
    jax.block_until_ready(out[2])
    print(f"{'serve_only':16s} {(time.time() - t0) / K * 1e3:9.1f} ms",
          flush=True)
    dsm.counters = ctr


if __name__ == "__main__":
    main()
