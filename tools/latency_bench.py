#!/usr/bin/env python
"""Width x depth latency frontier — the coroutine-depth analogue.

The reference hides per-op latency with 8-deep coroutine clients
(``Tree.cpp:1059-1122``): narrow per-op work, many in flight.  The
batched engine's analogue is NARROW STEPS, many in flight via JAX async
dispatch: a width-W routed-search step costs span(W) on chip, the host
keeps the dispatch queue non-empty, and in the step-span latency model an
op's completion latency is (batch-formation wait <= span) + (its step's
span) — p50 ~= 1.5 x span for an open loop admitting a batch every span.

This driver measures, per width:

- ``pipe_ms``    — pipelined ms/step (dispatch N, drain once): the
                   throughput-side truth, any queue depth.
- ``span_ms``    — per-step span from 64 block-amortized samples
                   (SHERMAN_BENCH_LAT_BLOCK steps per sync), minus the
                   CALIBRATED per-sync access-tunnel cost share; both raw
                   and adjusted are printed.  On a co-located host the
                   adjustment is ~0 and raw == adjusted.

Percentiles (round 7+) come from ``obs/slo.py`` trackers — the same
log-bucketed streaming estimator the SLO plane publishes — instead of
ad-hoc numpy arrays, so the latency-bracket chip re-capture and the
serving-side SLO window report through ONE code path (rank-interpolated
within <= 12.5% buckets; each row also gains p999 fields and an ``slo``
sub-dict with the tracker's own window view).
- ``ops_s``      — width / pipe_ms.
- ``p50_model``  — 1.5 x span (formation wait + service); the measured
                   span is the same quantity bench.py's p50 reports at
                   wide widths, where the sync share is negligible.
- ``p50_measured_raw`` / ``p50_measured`` — a MEASURED open-loop
                   async-dispatch client (wall-clock-paced admissions at
                   utilization ``--rho``, sampled completion drains)
                   brackets the true per-op latency: raw timestamps are
                   an upper bound (the observing drain adds <= 1 tunnel
                   RTT; co-located hosts read raw directly), the
                   calibrated-sync-subtracted values a lower bound.

Admissions are paced by the shared ``perf_counter_ns`` SLEEP+SPIN
hybrid (round 6; one copy in ``tools/common.py`` —
:class:`common.AdmissionPacer` — shared with ``tools/serve_bench.py``):
coarse sleep until ``--spin-ms`` before each deadline, then a spin
bounded at half the batch period — ms-granularity ``time.sleep`` could
not pace sub-ms periods, which is what kept the 16 K row below the
round-5 admission floor.  Every row publishes its per-admission pacing
error (``adm_jitter_p50/p99_ms``) and an ``adm_feasible`` verdict, so a
width whose jitter rivals its period is rejected by measurement, not by
prose.

Run: python tools/latency_bench.py [--keys 10000000]
         [--widths 16384,32768,65536,262144] [--blocks 64] [--kblk 32]
         [--spin-ms 2.0]
Prints ONE JSON line with the frontier.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from common import AdmissionPacer, setup_platform  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=10_000_000)
    ap.add_argument("--widths", type=str, default="16384,32768,65536,262144")
    ap.add_argument("--blocks", type=int, default=64,
                    help="latency block samples per width; also the "
                         "open-loop sample-count target (values below 8 "
                         "are honored as given — expect coarse "
                         "percentiles)")
    ap.add_argument("--kblk", type=int, default=32,
                    help="steps per latency block (one sync each)")
    ap.add_argument("--theta", type=float, default=0.99)
    ap.add_argument("--rho", type=float, default=0.85,
                    help="open-loop admission utilization (offered rate "
                         "/ service rate).  1.0 is marginally stable — "
                         "any stall grows the queue without bound")
    ap.add_argument("--spin-ms", type=float, default=2.0,
                    help="spin-wait window before each admission "
                         "deadline: the pacer sleeps until this close "
                         "to the deadline, then spins on "
                         "perf_counter_ns.  Bounded duty cycle: the "
                         "spin budget is additionally capped at half "
                         "the batch period, so pacing can never eat a "
                         "full core.  Per-admission error is published "
                         "(adm_jitter_*) as each row's feasibility "
                         "receipt")
    args = ap.parse_args()
    if args.blocks < 1:
        ap.error("--blocks must be >= 1 (percentiles need samples)")
    widths = [int(w) for w in args.widths.split(",")]

    jax = setup_platform(1)
    jax.config.update("jax_compilation_cache_dir", os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    import jax.numpy as jnp

    from sherman_tpu import native
    from sherman_tpu.cluster import Cluster
    from sherman_tpu.config import LEAF_CAP, DSMConfig, TreeConfig
    from sherman_tpu.models import batched
    from sherman_tpu.models.btree import Tree
    from sherman_tpu.obs import slo as SLO
    from sherman_tpu.ops import bits

    n_keys = args.keys
    assert native.available(), "latency bench needs the native lib"
    salt = 0x5E17_AB1E_5A17
    while True:
        try:
            keys, rank_to_key = native.synthetic_keyspace(n_keys, salt)
            break
        except ValueError:
            salt += 1
    fill = 0.75
    est = int(n_keys / int(LEAF_CAP * fill) * 1.10) + 8192
    pages = 1 << max(14, (est - 1).bit_length())
    Bmax = max(widths)
    cfg = DSMConfig(machine_nr=1, pages_per_node=pages,
                    locks_per_node=65_536, step_capacity=Bmax,
                    chunk_pages=4096)
    cluster = Cluster(cfg)
    tree = Tree(cluster)
    t0 = time.time()
    batched.bulk_load(tree, keys, keys ^ np.uint64(0xD00D), fill=fill)
    print(f"# bulk load {time.time() - t0:.1f}s", file=sys.stderr)

    # calibrate the per-sync tunnel cost: block_until_ready on an
    # already-materialized tiny array + a tiny jitted step, repeated
    one = jax.device_put(np.zeros(8, np.int32))
    tiny = jax.jit(lambda x: x + 1)
    tiny(one)
    rtts = []
    for _ in range(12):
        y = tiny(one)
        t1 = time.time()
        jax.block_until_ready(y)
        np.asarray(y[0])
        rtts.append(time.time() - t1)
    sync_ms = float(np.median(rtts)) * 1e3
    print(f"# calibrated per-sync cost {sync_ms:.1f} ms (tunnel; ~0 "
          "co-located)", file=sys.stderr)

    zg = native.ZipfGen(n_keys, args.theta, seed=29)
    rows = []
    for W in widths:
        eng = batched.BatchedEngine(tree, batch_per_node=W,
                                    tcfg=TreeConfig(sibling_chase_budget=1))
        router = eng.attach_router()
        fn = eng._get_search(eng._iters(), True)
        shard = tree.dsm.shard
        root = np.int32(tree._root_addr)
        pool, counters = tree.dsm.pool, tree.dsm.counters
        # pre-staged batches (latency mode serves pre-formed batches; the
        # sustained-prep story lives in bench.py)
        n_b = 32
        batches = []
        for i in range(n_b):
            k = rank_to_key[zg.sample(W)]
            khi, klo = bits.keys_to_pairs(k)
            st = router.host_start(khi, klo)
            batches.append((jax.device_put(khi, shard),
                            jax.device_put(klo, shard),
                            jax.device_put(st, shard)))
        act = jax.device_put(np.ones(W, bool), shard)

        def step(i, counters):
            b = batches[i % n_b]
            return fn(pool, counters, b[0], b[1], root, act, b[2])

        counters, done, found, vhi, vlo = step(0, counters)
        jax.block_until_ready(found)
        assert bool(np.asarray(found).all())
        for i in range(4):
            counters, done, found, vhi, vlo = step(i, counters)
        jax.block_until_ready(found)

        # pipelined throughput: N steps, one drain
        N = max(64, min(512, int(4e6 * 64 / W)))
        t1 = time.time()
        for i in range(N):
            counters, done, found, vhi, vlo = step(i, counters)
        jax.block_until_ready(found)
        pipe_ms = (time.time() - t1) / N * 1e3

        # block-amortized spans -> the SLO plane's own streaming
        # tracker (one estimator for the latency bench AND the serving
        # window; W ops per step at the per-step span is the same
        # amortized-wall attribution bench.py's slo section uses)
        span_t = SLO.SloTracker(window_s=3600.0)
        for b in range(args.blocks):
            t1 = time.time()
            for i in range(args.kblk):
                counters, done, found, vhi, vlo = step(i, counters)
            jax.block_until_ready(found)
            span_t.observe("read", W * args.kblk, time.time() - t1,
                           batches=args.kblk)
        span_w = span_t.window()["read"]
        raw50 = span_w["p50_ms"]
        raw99 = span_w["p99_ms"]
        adj = sync_ms / args.kblk
        span50 = max(pipe_ms, raw50 - adj)
        span99 = max(pipe_ms, raw99 - adj)
        ops_s = W / (pipe_ms / 1e3)

        # MEASURED open loop (the async-dispatch client the 1.5x-span
        # MODEL predicts; benchmark.cpp:159-188,207-249 parity).  Ops
        # arrive on a WALL-CLOCK schedule — batch i's ops arrive
        # uniformly over [t0+(i-1)*T, t0+i*T), T = pipe_ms (admission at
        # the service rate) — and batches dispatch when due, never
        # self-clocked.  A SAMPLE of batches gets a completion
        # timestamp: a blocking drain costs ~sync_ms of host time on
        # the access tunnel, so timestamping every batch would throttle
        # admission; every STRIDE-th batch keeps the drain duty cycle
        # under ~50% and the in-between batches pipeline freely (the
        # emergent dispatch queue IS the client's depth).
        #
        # Admission runs at utilization RHO < 1 (batch period T =
        # pipe_ms / rho): an open loop offered EXACTLY the service rate
        # is marginally stable — any stall (here: tunnel RPC jitter)
        # grows the queue without bound and the measurement diverges
        # (rho=1.0 measured p50 ~= the tunnel RTT at W=16K).  The
        # reference's own open loop is self-limiting the same way: its
        # clients cap in-flight ops at coroutine depth.
        #
        # A sampled batch's completion timestamp brackets the true
        # latency between two published numbers:
        #   raw      = t_complete - mean_arrival      (upper bound: the
        #              observing drain adds up to one tunnel RTT;
        #              co-located hosts read this directly)
        #   adjusted = raw - sync_ms, clamped >= 0    (lower bound: the
        #              calibrated MEDIAN RTT may exceed this sample's
        #              actual RTT, so the subtraction can overshoot)
        # On this environment service latencies are ms-scale while the
        # RTT is ~100-200 ms, so the bracket is wide here and tight
        # co-located — both ends are published per width.
        rho = args.rho
        T = pipe_ms / 1e3 / rho
        stride = max(1, int(np.ceil((sync_ms / 1e3) / T / 0.5)))
        # --blocks is the sample-count target here too, bounded by a
        # ~2000-dispatch budget per width (long strides on high-RTT
        # hosts would otherwise turn many samples into minutes).  An
        # explicit --blocks below 8 is honored as given (quick smoke
        # runs; the old 8-sample floor silently overrode it) — the
        # dispatch-budget bound is >= 16, so any --blocks <= 16 passes
        # through unchanged.
        n_samp = min(args.blocks, max(16, 2000 // stride))
        n_ol = n_samp * stride
        # open-loop samples stream into slo.LatencyTracker pairs (raw /
        # sync-adjusted) — the bracket's two ends through the same
        # estimator the SLO plane publishes
        ol_raw_t = SLO.LatencyTracker()
        ol_adj_t = SLO.LatencyTracker()
        # Admission pacing: the SHARED perf_counter_ns sleep+spin pacer
        # (common.AdmissionPacer — one copy for this driver and
        # serve_bench; the rationale and the jitter-receipt contract
        # live on the class).  Deadline i = t_base + i*T; per-admission
        # error is recorded and PUBLISHED (adm_jitter_p50/p99_ms) as
        # the row's admission-feasibility receipt.
        pacer = AdmissionPacer(T, spin_ms=args.spin_ms)
        T_ns = pacer.period_ns
        sync_ns = int(sync_ms * 1e6)
        pacer.start()
        for i in range(n_ol):
            pacer.wait_turn(i)
            counters, done, found, vhi, vlo = step(i, counters)
            if i % stride == stride - 1:
                jax.block_until_ready(found)
                t_c = time.perf_counter_ns()
                # arrivals are uniform over batch i's admission window,
                # so the sample's reference point is the MEAN arrival
                mean_arrival = pacer.due_ns(i) - T_ns // 2
                raw_ms = (t_c - mean_arrival) / 1e6
                ol_raw_t.record(raw_ms / 1e3)
                ol_adj_t.record(max(0.0, raw_ms - sync_ms) / 1e3)
                # RE-ANCHOR the admission schedule by the OBSERVER's
                # stall only (~sync_ms): without it, admissions accrue
                # against the drain-stalled clock and every later
                # sample measures accumulated observation backlog
                # (+~sync_ms per sample), not service latency.  Capped
                # at sync_ms so GENUINE service backlog — the device
                # falling behind the offered rate — still accumulates
                # across strides exactly as in a true open loop
                # (uncapped re-anchoring would reintroduce coordinated
                # omission).  AdmissionPacer.absorb_stall is this exact
                # rule.
                pacer.absorb_stall(i + 1, sync_ns)
        adm = pacer.jitter_receipt()
        adm_p50 = adm["adm_jitter_p50_ms"]
        adm_p99 = adm["adm_jitter_p99_ms"]
        # feasibility: admissions held the offered schedule if the p99
        # pacing error is small against the batch period
        adm_ok = adm["adm_feasible"]
        spin_ns = pacer.spin_ns
        # each sample is a batch-MEAN op latency; op arrivals are
        # uniform over a T-wide window, so op-level tails spread
        # +-T/2 around the batch mean.  p50 is unaffected (symmetric);
        # p99 adds ~0.48*T (the 98th pct of U[-T/2, T/2]) — published
        # op-level, not batch-level.
        p50_raw_m = ol_raw_t.percentile_ms(50)
        p99_raw_m = ol_raw_t.percentile_ms(99) + 0.48 * T * 1e3
        p50_meas = ol_adj_t.percentile_ms(50)
        p99_meas = ol_adj_t.percentile_ms(99) + 0.48 * T * 1e3
        n_lat = ol_raw_t.count
        row = {
            "width": W,
            "pipe_ms": round(pipe_ms, 2),
            "span_p50_raw_ms": round(raw50, 2),
            "span_p50_ms": round(span50, 2),
            "span_p99_ms": round(span99, 2),
            "ops_s": round(ops_s),
            "p50_model_ms": round(1.5 * span50, 2),
            # measured open-loop bracket (see comment above): raw =
            # upper bound incl. <= 1 tunnel RTT (co-located hosts read
            # this directly), plain = sync-adjusted lower bound
            "p50_measured_raw_ms": round(p50_raw_m, 2),
            "p99_measured_raw_ms": round(p99_raw_m, 2),
            "p50_measured_ms": round(p50_meas, 2),
            "p99_measured_ms": round(p99_meas, 2),
            # SLO-plane extras: the tracker resolves p999 for free, and
            # the span tracker's window is published whole so this row
            # and bench.py's "slo" section are the same estimator
            "span_p999_ms": round(span_t.window()["read"]["p999_ms"], 2),
            "p999_measured_raw_ms": round(
                ol_raw_t.percentile_ms(99.9) + 0.48 * T * 1e3, 2),
            "slo": {k: round(float(v), 3)
                    for k, v in span_w.items()},
            "percentile_source": "obs.slo.LatencyTracker",
            "ol_samples": n_lat,
            "ol_stride": stride,
            "ol_rho": rho,
            "sync_share_ms": round(adj, 2),
            # admission-pacing receipts (perf_counter_ns spin-wait):
            # dispatch-vs-schedule error percentiles and the spin
            # budget actually used.  adm_feasible=false flags a row
            # whose pacing error rivals its batch period — its
            # measured bracket reflects admission backlog, not
            # service latency, and must be read accordingly.
            "adm_jitter_p50_ms": round(adm_p50, 3),
            "adm_jitter_p99_ms": round(adm_p99, 3),
            "adm_spin_budget_ms": round(spin_ns / 1e6, 3),
            "adm_feasible": bool(adm_ok),
            "pacing": "sleep+spin",
        }
        rows.append(row)
        print(f"# W={W:>7}: pipe {pipe_ms:6.2f} ms/step -> "
              f"{ops_s / 1e6:5.1f} M ops/s; span p50 {span50:5.2f} ms "
              f"(raw {raw50:5.2f} - sync/blk {adj:4.2f}), p99 "
              f"{span99:5.2f}; open-loop p50 model {1.5 * span50:5.2f} ms "
              f"vs MEASURED [{p50_meas:5.2f}, {p50_raw_m:6.2f}] ms "
              f"(p99 [{p99_meas:5.2f}, {p99_raw_m:6.2f}], "
              f"{n_lat} samples, stride {stride}, rho {rho}; "
              f"adm jitter p50 {adm_p50:.3f} / p99 {adm_p99:.3f} ms, "
              f"spin {spin_ns / 1e6:.2f} ms, "
              f"{'feasible' if adm_ok else 'NOT FEASIBLE'})",
              file=sys.stderr)
        tree.dsm.counters = counters

    best = [r for r in rows if r["ops_s"] >= 10_000_000]
    best = min(best, key=lambda r: r["p50_model_ms"]) if best else None
    # model honesty: does the model's p50 land inside the measured
    # [adjusted, raw] bracket per width?  (On a co-located host the
    # bracket collapses to a point and this becomes a direct check.)
    in_bracket = [r["p50_measured_ms"] <= r["p50_model_ms"]
                  <= r["p50_measured_raw_ms"] for r in rows]
    out = {
        "metric": "latency_frontier",
        "sync_ms": round(sync_ms, 1),
        "rows": rows,
        "best_10M": best,
        # per-width: model p50 inside the measured [adjusted, raw]
        # bracket (lower bound subtracts the calibrated tunnel RTT,
        # upper includes <= 1 RTT; see the open-loop comment)
        "model_p50_in_measured_bracket": in_bracket,
        "keys": n_keys,
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
