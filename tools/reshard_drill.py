#!/usr/bin/env python
"""Reshard drill: live N→M grow under mixed traffic, crash-restartable.

The capacity drill — third end-to-end rehearsal beside the chaos drill
(detection) and the recovery drill (durability):

  phase 1  build + bulk-load an N-node CPU mesh, start the recovery
           plane (base checkpoint + journal) and the online migrator
           (``sherman_tpu/migrate.py``) toward M nodes.
  phase 2  MIXED acknowledged traffic (inserts, deletes, reads)
           interleaved with bounded migration batches — the migrator
           lock-copies live pages under its own lease while the engine
           serves; a delta checkpoint lands mid-stream (the migration's
           dirty re-copy set rides the clear through the DSM dirty
           sink).  Per-op-class p99 is sampled from the PR 7 SLO plane
           before and during migration — the published "bounded p99
           spike" receipt.
  chaos +  a seeded FaultPlan wedges a lock as held-by-a-dead-client
  crash    mid-migration (the migrator must revoke it to keep copying),
           then the cluster is dropped cold with a torn journal tail.
  recover  ``RecoveryPlane.recover`` (RPO 0 against the acked-op
           ledger), then ``Migrator.resume``: completed batches are
           re-verified from their CRC-tagged artifacts, not re-done.
  finish   more acked traffic, migration completes, quiesced cutover
           emits the M-node checkpoint.
  validate the emitted pool must be BIT-IDENTICAL to the offline
           ``tools/reshard.py`` transform of the same final logical
           state (same transform by construction — the pin proves the
           staged image lost zero writes), and the restored M-node
           cluster must serve every acknowledged op: ``lost_acks == 0``.

Runs on the CPU mesh anywhere (``bench.py --reshard-drill`` forwards
here; ``scripts/reshard_ci.sh`` pins it in CI).  Prints ONE JSON line
``{"metric": "reshard_drill", "ok": true, "lost_acks": 0, "rpo_ops": 0,
"bit_identical": true, ...}`` and mirrors it to
``SHERMAN_RESHARD_RECEIPT`` when set.  Env knobs: SHERMAN_DRILL_KEYS
(default 4000), SHERMAN_DRILL_NODES (source N, default 4),
SHERMAN_DRILL_TARGET_NODES (target M, default 6), SHERMAN_CHAOS_SEED,
SHERMAN_MIGRATE_BATCH_PAGES (migration batch size).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

from common import build_cluster, pages_for_keys, setup_platform


def _p99(window: dict, op_class: str) -> float:
    rec = (window or {}).get(op_class) or {}
    return float(rec.get("p99_ms") or 0.0)


def main(argv=None) -> dict:
    p = argparse.ArgumentParser()
    p.add_argument("--keys", type=int,
                   default=int(os.environ.get("SHERMAN_DRILL_KEYS", 4000)))
    p.add_argument("--nodes", type=int,
                   default=int(os.environ.get("SHERMAN_DRILL_NODES", 4)))
    p.add_argument("--target-nodes", type=int,
                   default=int(os.environ.get("SHERMAN_DRILL_TARGET_NODES",
                                              6)))
    p.add_argument("--seed", type=int,
                   default=int(os.environ.get("SHERMAN_CHAOS_SEED", 7)))
    p.add_argument("--batch-pages", type=int,
                   default=int(os.environ.get("SHERMAN_MIGRATE_BATCH_PAGES",
                                              32)),
                   help="migration batch size (small, so the copy "
                        "genuinely interleaves with the drill traffic)")
    p.add_argument("--dir", default=None,
                   help="drill directory (default: a tempdir)")
    a = p.parse_args(argv)
    setup_platform(max(a.nodes, a.target_nodes))

    from sherman_tpu import chaos as CH
    from sherman_tpu import obs
    from sherman_tpu.config import TreeConfig
    from sherman_tpu.migrate import Migrator
    from sherman_tpu.models import batched
    from sherman_tpu.models.btree import Tree
    from sherman_tpu.models.validate import check_structure_device
    from sherman_tpu.recovery import RecoveryPlane
    from sherman_tpu.utils import checkpoint as CK
    from sherman_tpu.utils import journal as J
    from sherman_tpu.utils.reshard import reshard

    t_start = time.time()
    out: dict = {"metric": "reshard_drill", "seed": a.seed, "ok": False,
                 "nodes": a.nodes, "target_nodes": a.target_nodes}
    root = a.dir or tempfile.mkdtemp(prefix="sherman_reshard_")
    rdir = os.path.join(root, "recovery")
    mdir = os.path.join(root, "migration")
    out["dir"] = root

    # -- phase 1: build + arm recovery plane + migrator -----------------------
    ppn = pages_for_keys(a.keys)
    cluster, tree, eng = build_cluster(
        a.nodes, ppn, batch_per_node=512,
        locks_per_node=1024, chunk_pages=64)
    rng = np.random.default_rng(3)
    keys = np.unique(rng.integers(1, 1 << 56, int(a.keys * 1.05),
                                  dtype=np.uint64))[:a.keys]
    vals = keys ^ np.uint64(0xE1A57C)
    batched.bulk_load(tree, keys, vals)
    eng.attach_router()
    check_structure_device(tree)
    plane = RecoveryPlane(cluster, tree, eng, rdir)
    plane.checkpoint_base()
    mig = Migrator(cluster, tree, eng, a.target_nodes, mdir,
                   target_pages_per_node=ppn, batch_pages=a.batch_pages)
    out["migration"] = mig.start()
    snap0 = obs.snapshot()

    # acked-op ledger: every (key -> value | None=deleted) whose engine
    # op RETURNED before the crash — the lost-ack audit set
    acked: dict = {}

    def ack_insert(ks, vs):
        st = eng.insert(ks, vs)
        assert st["lock_timeouts"] == 0, st
        for k, v in zip(ks.tolist(), vs.tolist()):
            acked[k] = v

    def ack_delete(ks):
        gone = eng.delete(ks)
        assert gone.all()
        for k in ks.tolist():
            acked[k] = None

    # baseline read p99 (traffic only, no migration interleaved).  The
    # first searches compile the read programs; reset the SLO window
    # after the warmup so neither sample is a compile wall in disguise.
    from sherman_tpu.obs import slo as SLO
    for i in range(4):
        eng.search(keys[i::97])
    SLO.get_slo().reset()
    for i in range(6):
        eng.search(keys[i::61])
    p99_before = _p99(obs.slo_window(), "read")
    SLO.get_slo().reset()

    # -- phase 2: mixed traffic x migration batches ---------------------------
    nb = max(64, a.keys // 10)
    i = 0
    rounds = 0
    while i < 3 * nb:
        mig.step()
        rounds += 1
        b = keys[i: i + nb // 2]
        ack_insert(b, b ^ np.uint64(0x1111))
        eng.search(keys[(i + rounds) % nb:: 61])
        i += nb // 2
    ack_delete(keys[3 * nb: 3 * nb + nb // 4])
    d1 = plane.checkpoint_delta()  # the dirty sink rides this clear
    out["delta1"] = {"pages": d1["pages"]}
    while not mig.copied_all and rounds < 10_000:
        mig.step()
        rounds += 1
        eng.search(keys[rounds % nb:: 53])
    p99_during = _p99(obs.slo_window(), "read")
    # the "bounded p99 spike" receipt: reads keep flowing while the
    # migrator holds batch locks — the spike is the lock-hold +
    # interleave tax, published for the trajectory (the hard pins are
    # lost_acks/rpo/bit-identity; CPU-mesh walls are too noisy to gate)
    out["slo"] = {"read_p99_before_ms": round(p99_before, 3),
                  "read_p99_during_ms": round(p99_during, 3),
                  "read_p99_spike": round(p99_during / p99_before, 2)
                  if p99_before > 0 else None}
    pre_crash_moved = mig.pages_moved
    assert pre_crash_moved > 0 and mig.batches > 1

    # -- chaos mid-migration: wedged lock the migrator must revoke ------------
    plan = CH.FaultPlan([CH.Fault(kind="wedge_lock", step=0)], seed=a.seed)
    cluster.dsm.install_chaos(plan)
    cluster.dsm.read_word(0, 0)
    cluster.dsm.install_chaos(None)
    b = keys[3 * nb + nb // 4: 4 * nb]
    ack_insert(b, b ^ np.uint64(0x2222))
    mig.step()  # copies through the wedged word via lease revocation

    # -- crash: drop the cluster cold, tear the journal tail ------------------
    jpath = eng.journal.path
    plane.close()
    mig.close()
    with open(jpath, "ab") as f:  # crash mid-append: torn half-record
        rec = J.encode_record(J.J_UPSERT, np.asarray([1 << 40], np.uint64),
                              np.asarray([7], np.uint64))
        f.write(rec[: len(rec) // 2])
    del cluster, tree, eng

    # -- recover + resume -----------------------------------------------------
    t0 = time.perf_counter()
    plane, cluster, tree, eng, rec = RecoveryPlane.recover(
        rdir, batch_per_node=512,
        tcfg=TreeConfig(sibling_chase_budget=1))
    out["recover"] = {"total_ms": rec["total_ms"],
                      "replayed": rec["replay"]["records"]}
    mig = Migrator.resume(cluster, tree, eng, mdir,
                          batch_pages=a.batch_pages)
    out["resume"] = {"staged": mig.staged_pages,
                     "resume_count": mig.resume_count}
    assert mig.resume_count == 1

    # RPO audit on the recovered source
    live = {k: v for k, v in acked.items() if v is not None}
    lk = np.asarray(sorted(live), np.uint64)
    got, found = eng.search(lk)
    rpo = int((~found).sum()) + int(
        (got[found] != np.asarray([live[int(k)] for k in lk],
                                  np.uint64)[found]).sum())
    dk = np.asarray([k for k, v in acked.items() if v is None], np.uint64)
    if dk.size:
        _, dfound = eng.search(dk)
        rpo += int(dfound.sum())
    out["rpo_ops"] = rpo
    obs.gauge("recovery.rpo_ops").set(rpo)
    assert rpo == 0, f"RPO violated: {rpo} acknowledged ops lost"
    out["rto_ms"] = round((time.perf_counter() - t0) * 1e3, 1)

    # -- finish: more acked traffic, complete, quiesced cutover ---------------
    b = keys[4 * nb: 5 * nb]
    ack_insert(b, b ^ np.uint64(0x3333))
    mig.run_to_copied()
    dst = os.path.join(mdir, "online.npz")
    summary = mig.finish(dst)
    assert mig.resume_verified > 0, \
        "resume re-verified nothing: batches were re-done, not resumed"
    out["cutover"] = {k: summary[k] for k in (
        "live_pages", "pages_moved", "batches", "retries",
        "lock_conflicts", "resume_verified", "cutover_ms")}

    # -- validate 1: bit-identity with the OFFLINE transform ------------------
    src_final = os.path.join(root, "final_src.npz")
    CK.checkpoint(cluster, src_final)
    offline = os.path.join(root, "offline.npz")
    reshard(src_final, offline, a.target_nodes, pages_per_node=ppn)
    ident = True
    with np.load(dst) as on, np.load(offline) as off:
        for k in ("pool", "locks", "counters", "dir_nodes", "dir_next",
                  "dir_root", "dir_free"):
            if not np.array_equal(on[k], off[k]):
                ident = False
                out.setdefault("identity_mismatch", []).append(k)
    out["bit_identical"] = ident
    assert ident, f"online pool != offline reshard: {out.get('identity_mismatch')}"

    # -- validate 2: the M-node cluster serves every acknowledged op ----------
    c2 = CK.restore(dst)
    t2 = Tree(c2)
    e2 = batched.BatchedEngine(t2, batch_per_node=512,
                               tcfg=TreeConfig(sibling_chase_budget=1))
    e2.attach_router()
    info = check_structure_device(t2)
    got, found = e2.search(lk)
    lost = int((~found).sum()) + int(
        (got[found] != np.asarray([live[int(k)] for k in lk],
                                  np.uint64)[found]).sum())
    if dk.size:
        _, dfound = e2.search(dk)
        lost += int(dfound.sum())
    # untouched bulk keys ride along too
    probe = keys[5 * nb:: max(1, a.keys // 512)]
    probe = probe[~np.isin(probe, np.asarray(list(acked), np.uint64))]
    got, found = e2.search(probe)
    lost += int((~found).sum()) + int(
        (got[found] != (probe ^ np.uint64(0xE1A57C))[found]).sum())
    out["lost_acks"] = lost
    assert lost == 0, f"{lost} acknowledged ops lost across the reshard"
    assert info["keys"] > 0
    # the new shape accepts writes (capacity actually grew)
    st = e2.insert(keys[:8], keys[:8])
    assert st["applied"] + st["superseded"] == 8

    d = obs.delta(snap0, obs.snapshot())
    out["obs"] = {k: int(d[k]) for k in sorted(d)
                  if k in ("migrate.pages_moved", "migrate.batches",
                           "migrate.retries", "migrate.lock_conflicts",
                           "migrate.resume_count",
                           "migrate.resume_verified", "migrate.epoch",
                           "lease.revoked")}
    out["elapsed_s"] = round(time.time() - t_start, 1)
    out["ok"] = True
    plane.close()
    line = json.dumps(out)
    print(line)
    receipt = os.environ.get("SHERMAN_RESHARD_RECEIPT")
    if receipt:
        with open(receipt, "w") as f:
            f.write(line + "\n")
    print("RESHARD-DRILL PASS", file=sys.stderr)
    return out


if __name__ == "__main__":
    main()
