"""Shared plumbing for the CLI drivers (the ``test/*.cpp`` role).

Every reference test binary begins with ``DSM::getInstance`` +
``registerThread`` + ``new Tree`` (e.g. ``test/benchmark.cpp:253-266``);
this module is that prologue: platform selection, cluster construction,
and tree/engine setup from CLI-ish knobs.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def setup_platform(n_nodes: int):
    """Pick the backend: n_nodes == 1 uses the default platform (the real
    chip when present); n_nodes > 1 forces an n-node virtual CPU mesh (the
    in-process multi-node backend, SURVEY.md §4's fake-transport lesson)
    unless SHERMAN_PLATFORM overrides.  Must run before the first jax
    device query — a devices() call initializes the backend and freezes
    XLA_FLAGS."""
    platform = os.environ.get("SHERMAN_PLATFORM", "")
    if n_nodes > 1 and not platform:
        platform = "cpu"
    if platform == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_nodes}"
            ).strip()
    import jax
    if platform:
        jax.config.update("jax_platforms", platform)
    devs = jax.devices()
    assert len(devs) >= n_nodes, (
        f"need {n_nodes} devices, have {len(devs)}")
    return jax


def build_cluster(n_nodes: int, pages_per_node: int, batch_per_node: int,
                  locks_per_node: int = 65_536, chunk_pages: int = 4096,
                  exchange_impl: str = "xla"):
    from sherman_tpu.cluster import Cluster
    from sherman_tpu.config import DSMConfig, TreeConfig
    from sherman_tpu.models import batched
    from sherman_tpu.models.btree import Tree

    cfg = DSMConfig(machine_nr=n_nodes, pages_per_node=pages_per_node,
                    locks_per_node=locks_per_node,
                    step_capacity=batch_per_node, chunk_pages=chunk_pages,
                    exchange_impl=exchange_impl)
    cluster = Cluster(cfg)
    tree = Tree(cluster)
    eng = batched.BatchedEngine(tree, batch_per_node=batch_per_node,
                                tcfg=TreeConfig(sibling_chase_budget=1))
    return cluster, tree, eng


def pages_for_keys(n_keys: int, fill: float = 0.75) -> int:
    from sherman_tpu.config import LEAF_CAP
    per_leaf = max(1, int(LEAF_CAP * fill))
    est = int(n_keys / per_leaf * 1.10) + 8192
    return 1 << max(12, (est - 1).bit_length())


class AdmissionPacer:
    """The round-6 ``perf_counter_ns`` SLEEP+SPIN admission pacer, in ONE
    copy shared by ``tools/latency_bench.py`` and ``tools/serve_bench.py``
    (the open-loop drivers' wall-clock schedule).

    ms-granularity ``time.sleep`` cannot pace sub-ms periods — the
    round-5 16 K latency row sat below the host's ADMISSION floor purely
    because sleep() quantizes at ~1-16 ms.  The hybrid sleeps until
    ``spin_ns`` before each deadline, then spins on the ns clock.  The
    spin budget is capped at HALF the period, so pacing can never eat a
    whole core busy-waiting.

    Every admission's pacing error (dispatch time − due time) is
    recorded; :meth:`jitter_receipt` publishes the p50/p99 percentiles
    plus an ``adm_feasible`` verdict (p99 error small against the
    period) — a row/phase whose jitter rivals its period was NOT paced
    at the offered rate, and the receipt says so instead of a prose
    rejection note.

    Usage::

        pacer = AdmissionPacer(period_s, spin_ms=2.0)
        pacer.start()                 # schedule anchored 2 periods out
        for i in range(n):
            pacer.wait_turn(i)        # blocks until deadline i
            ... dispatch ...
            pacer.absorb_stall(i + 1, cap_ns)  # optional: re-anchor
                                      # after an OBSERVER stall
                                      # (ns-capped — see the
                                      # coordinated-omission note)

    Thread contract: one pacer paces ONE admission stream (per-thread
    instances for multi-tenant drivers); ``jitter_receipt`` may merge
    several pacers' errors via ``merge_errors``.
    """

    def __init__(self, period_s: float, spin_ms: float = 2.0):
        import time
        assert period_s > 0
        self._clock = time.perf_counter_ns
        self._sleep = time.sleep
        self.period_ns = int(period_s * 1e9)
        # duty-cycle bound: never spin more than half the period
        self.spin_ns = int(min(spin_ms * 1e6, 0.5 * self.period_ns))
        self.errors_ns: list[int] = []
        self._t_base: int | None = None

    def start(self, lead_periods: int = 2) -> None:
        """Anchor the schedule ``lead_periods`` periods from now (slack
        for the first dispatch's setup)."""
        self._t_base = self._clock() + lead_periods * self.period_ns

    def due_ns(self, i: int) -> int:
        assert self._t_base is not None, "call start() first"
        return self._t_base + i * self.period_ns

    def wait_turn(self, i: int) -> int:
        """Block (sleep, then spin) until deadline ``i``; returns and
        records the pacing error in ns (>= 0: late dispatch)."""
        due = self.due_ns(i)
        now = self._clock()
        if now < due - self.spin_ns:
            self._sleep((due - self.spin_ns - now) / 1e9)
        while True:
            now = self._clock()
            if now >= due:
                break
        err = now - due
        self.errors_ns.append(err)
        return err

    def absorb_stall(self, next_i: int, cap_ns: int) -> None:
        """Re-anchor the schedule by at most ``cap_ns`` after an
        OBSERVER stall (a blocking drain on the measurement path).
        Uncapped re-anchoring would reintroduce coordinated omission —
        genuine service backlog must keep accumulating; only the
        observation cost is forgiven (latency_bench caps at the
        calibrated sync RTT)."""
        lag = self._clock() - self.due_ns(next_i)
        if lag > 0:
            self._t_base += min(lag, cap_ns)

    def merge_errors(self, other: "AdmissionPacer") -> None:
        self.errors_ns.extend(other.errors_ns)

    def jitter_receipt(self, feasible_frac: float = 0.25) -> dict:
        """{adm_jitter_p50_ms, adm_jitter_p99_ms, adm_spin_budget_ms,
        adm_feasible, pacing} — each open-loop row/phase's
        admission-feasibility receipt."""
        import numpy as np
        errs = self.errors_ns or [0]
        p50 = float(np.percentile(errs, 50)) / 1e6
        p99 = float(np.percentile(errs, 99)) / 1e6
        return {
            "adm_jitter_p50_ms": round(p50, 3),
            "adm_jitter_p99_ms": round(p99, 3),
            "adm_spin_budget_ms": round(self.spin_ns / 1e6, 3),
            "adm_feasible": bool(
                p99 < feasible_frac * self.period_ns / 1e6),
            "pacing": "sleep+spin",
        }
