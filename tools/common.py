"""Shared plumbing for the CLI drivers (the ``test/*.cpp`` role).

Every reference test binary begins with ``DSM::getInstance`` +
``registerThread`` + ``new Tree`` (e.g. ``test/benchmark.cpp:253-266``);
this module is that prologue: platform selection, cluster construction,
and tree/engine setup from CLI-ish knobs.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def setup_platform(n_nodes: int):
    """Pick the backend: n_nodes == 1 uses the default platform (the real
    chip when present); n_nodes > 1 forces an n-node virtual CPU mesh (the
    in-process multi-node backend, SURVEY.md §4's fake-transport lesson)
    unless SHERMAN_PLATFORM overrides.  Must run before the first jax
    device query — a devices() call initializes the backend and freezes
    XLA_FLAGS."""
    platform = os.environ.get("SHERMAN_PLATFORM", "")
    if n_nodes > 1 and not platform:
        platform = "cpu"
    if platform == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_nodes}"
            ).strip()
    import jax
    if platform:
        jax.config.update("jax_platforms", platform)
    devs = jax.devices()
    assert len(devs) >= n_nodes, (
        f"need {n_nodes} devices, have {len(devs)}")
    return jax


def build_cluster(n_nodes: int, pages_per_node: int, batch_per_node: int,
                  locks_per_node: int = 65_536, chunk_pages: int = 4096,
                  exchange_impl: str = "xla"):
    from sherman_tpu.cluster import Cluster
    from sherman_tpu.config import DSMConfig, TreeConfig
    from sherman_tpu.models import batched
    from sherman_tpu.models.btree import Tree

    cfg = DSMConfig(machine_nr=n_nodes, pages_per_node=pages_per_node,
                    locks_per_node=locks_per_node,
                    step_capacity=batch_per_node, chunk_pages=chunk_pages,
                    exchange_impl=exchange_impl)
    cluster = Cluster(cfg)
    tree = Tree(cluster)
    eng = batched.BatchedEngine(tree, batch_per_node=batch_per_node,
                                tcfg=TreeConfig(sibling_chase_budget=1))
    return cluster, tree, eng


def pages_for_keys(n_keys: int, fill: float = 0.75) -> int:
    from sherman_tpu.config import LEAF_CAP
    per_leaf = max(1, int(LEAF_CAP * fill))
    est = int(n_keys / per_leaf * 1.10) + 8192
    return 1 << max(12, (est - 1).bit_length())
